"""Host-side supervisor for process-isolated fleet workers (ISSUE 14).

`ProcessFleet` is the cross-process sibling of `Fleet`: N replica
WORKER PROCESSES (worker.py) each hosting one ServingEngine, driven
over the framed TCPStore mailbox (transport.py). The failure domain
shrinks from "the process" to "one worker": a kill -9, OOM-kill or
wedged device loop loses one engine, and the supervisor re-lands its
in-flight requests on survivors with the same zero-loss, exactly-once
contract the in-process fleet has.

How exactly-once survives a real wire:

* the supervisor OWNS request ids and full request records; a submit
  is the adoption of a fresh record on the routed worker;
* token events carry per-request stream indices; the **funnel** only
  delivers index == len(tokens): duplicated deliveries (the
  `transport.duplicate` fault) are discarded by index (value-checked —
  a mismatch would mean non-deterministic regeneration and is counted
  as a conflict), out-of-order arrivals buffer until their prefix
  lands;
* every heartbeat ships an incremental snapshot (prompt + tokens so
  far per live request). When a worker dies un-gracefully the
  supervisor merges (last shipped snapshot, tokens the funnel already
  delivered) — catch-up tokens flow through the same funnel — and
  adopts the request on a survivor from the LONGEST VERIFIED prefix.
  The successor re-emits any overlap deterministically (greedy + same
  bucket grid + same seeded weights) and the funnel drops it by
  index. Dropped event messages (`transport.drop`) heal the same way:
  the next snapshot carries the tokens the events lost.

Suspicion ladder (host wall clock, injectable): a missed heartbeat
past `suspect_after_s` marks the worker SUSPECT (visible as
`heartbeat_gap_seconds` in the Prometheus text — the rolling-restart
acceptance signal); past `dead_after_s` (or on process exit) the
supervisor SIGKILLs what's left and adopts from the last snapshot. A
deliberate `drain()` asks the worker to snapshot-and-exit gracefully,
and `rolling_restart()` chains drain -> respawn -> adopt — with a
shared `compile_cache_dir` in the worker spec the successor skips the
bucket-grid compile storm (serving/compile_cache.py).

Worker processes are always spawned CPU-pinned with the TPU grant env
scrubbed unless the spec says otherwise — on real chips the
one-TPU-process rule means per-process device grants, which is
deployment plumbing, not this module's business.

**Disaggregated prefill/decode (ISSUE 18).** A worker spec may carry
`role`: "prefill" / "decode" / "both" (default). Role-aware routing
(`router.role_candidates`) sends fresh submits to prefill-capable
workers and re-lands already-prefilled records on decode-capable ones,
FALLING BACK to whoever is healthy when a role is starved. A
prefill-role engine finishes each request with reason "handoff" after
its last prefill chunk + first token; the worker ships `prefill_done
{rid, output_ids, prefix_len}` and the supervisor drives the KV
handoff as a per-request state machine keyed by pull_id:

    PULLING    kv_pull sent to the donor (prefill worker)
    STREAMING  donor's kv_prefix seen; kv_page frames relayed verbatim
               to the chosen decode worker as they arrive
    ADOPT_WAIT every frame relayed; waiting on the target's kv_adopted
    BACKOFF    a phase deadline passed; capped exponential backoff,
               then re-issue under a fresh pull_id

Every phase has a deadline (`handoff_timeout_s`, reset on progress)
and every failure degrades instead of shedding: donor death parks the
request through the normal evacuation path (it stays ASSIGNED to the
donor until placement, so the existing machinery covers it); target
death re-routes to a survivor; attempts exhausted -> the target adopts
the record WITHOUT pages and re-prefills from its own radix/weights
(bit-identical — the same determinism contract migration relies on);
no decode-capable worker at all -> the record re-lands co-located on
the donor with `colocate=True` (its radix still holds the prefix, so
the re-prefill is a cache hit). After a confirmed adoption the donor
gets `kv_release` so the shipped prefix becomes its coldest eviction
victim. Fault point `fleet.handoff_stall` (registered here, fired at
the kv_page relay) discards a relayed frame so the stream wedges and
the phase timeout must recover.

Module import stays jax-free (FleetHandle/event shapes import lazily):
the supervisor side can run in a process that never touches jax.
"""
from __future__ import annotations

import enum
import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ...utils import faults
from .router import role_candidates
from .transport import Channel, TransportError, bind_store, free_port

# The B2 protocol rule cross-checks every message type sent here
# against the worker's dispatch (and vice versa):
# tpu-lint-hint: protocol-peer=worker.py

__all__ = ["ProcessFleet", "WorkerProc", "WorkerState",
           "FAULT_HANDOFF_STALL"]

# Fired at the supervisor's kv_page relay site: any payload -> the
# frame is NOT relayed, so the decode worker's intake never completes
# and the handoff wedges mid-stream — the phase timeout must notice,
# abort the intake, and recover (backoff re-pull or pageless adopt).
FAULT_HANDOFF_STALL = faults.register_point("fleet.handoff_stall")


class WorkerState(enum.Enum):
    SPAWNING = "spawning"    # process launched, ready not yet seen
    HEALTHY = "healthy"      # in rotation
    SUSPECT = "suspect"      # heartbeat gap past suspect_after_s
    DRAINING = "draining"    # deliberate drain in flight
    STOPPED = "stopped"      # graceful exit observed (bye)
    DEAD = "dead"            # un-graceful death; evacuated


class WorkerProc:
    """One worker process + its channel + liveness bookkeeping."""

    def __init__(self, name: str, spec: dict, store, *,
                 python: Optional[str] = None, generation: int = 0):
        self.name = name
        self.spec = dict(spec)
        self.generation = int(generation)
        session = f"{spec.get('session_base', 's0')}/{name}/g{generation}"
        self.spec["session"] = session
        self.spec["name"] = name
        # fleet role (ISSUE 18): "prefill" / "decode" / "both". The
        # spec's top-level role is mirrored into the engine kwargs so
        # a prefill worker's ENGINE also runs in handoff mode.
        self.role = str(spec.get("role")
                        or spec.get("engine", {}).get("role", "both"))
        if self.role != "both":
            eng = dict(self.spec.get("engine", {}))
            eng.setdefault("role", self.role)
            self.spec["engine"] = eng
        self.chan = Channel(store, me="host", peer=name, session=session)
        self.state = WorkerState.SPAWNING
        self.pid: Optional[int] = None
        self.ready = False
        self.last_beat_host_t: Optional[float] = None
        self.last_beat: Optional[dict] = None
        self.last_snapshot: Optional[dict] = None
        self.last_stats: Optional[dict] = None
        self.pongs = 0
        self.fired: Dict[str, int] = {}
        self.reported_load = 0
        self.beats = 0
        self._spec_path = None
        self._proc: Optional[subprocess.Popen] = None
        self._python = python or sys.executable
        self._draining_mailbox = False

    def spawn(self, *, extra_env: Optional[dict] = None,
              stderr_path: Optional[str] = None):
        fd, self._spec_path = tempfile.mkstemp(suffix=".json",
                                               prefix=f"ptw_{self.name}_")
        with os.fdopen(fd, "w") as f:
            json.dump(self.spec, f)
        env = dict(os.environ)
        # never let a worker claim the single-client TPU grant or the
        # parent's 8-virtual-device XLA flags by accident (CLAUDE.md
        # environment rules); the spec can override deliberately
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = self.spec.get("platform", "cpu")
        env.update(extra_env or {})
        if stderr_path:
            os.makedirs(os.path.dirname(stderr_path) or ".",
                        exist_ok=True)
        err = open(stderr_path, "ab") if stderr_path else subprocess.DEVNULL
        try:
            self._proc = subprocess.Popen(
                [self._python, "-m", "paddle_tpu.serving.fleet.worker",
                 "--spec", self._spec_path],
                env=env, stdout=err, stderr=err,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))))
        finally:
            if err is not subprocess.DEVNULL:
                err.close()
        self.pid = self._proc.pid
        return self

    # ---- liveness --------------------------------------------------------
    def poll(self) -> Optional[int]:
        return self._proc.poll() if self._proc is not None else None

    def kill(self, sig=None):
        if self._proc is not None and self._proc.poll() is None:
            import signal as _signal
            self._proc.send_signal(
                sig if sig is not None else _signal.SIGKILL)

    def terminate(self):
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self._proc is None:
            return None
        try:
            return self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def cleanup(self):
        if self._spec_path:
            try:
                os.remove(self._spec_path)
            except OSError:
                pass
            self._spec_path = None


class ProcessFleet:
    """Submit/pump facade over N worker processes.

    `worker_specs` is {name: spec}; each spec carries the model/engine
    config worker.py builds from (plus optional compile_cache_dir,
    heartbeat_interval_s, faults, snapshot_path). The store endpoint
    is bound here (the supervisor is rank 0 of the mailbox store).

    The supervisor is SYNCHRONOUS like Fleet: `pump()` is one
    iteration (drain every worker's mailbox, run the suspicion
    ladder, re-land parked work); `run()` loops pump until every
    tracked handle finishes. `clock` injects the suspicion clock for
    tests; worker heartbeats ride their own process clocks and are
    judged only by host-side RECEIPT gaps, so clock skew between
    processes cannot false-positive the ladder.
    """

    def __init__(self, worker_specs: Dict[str, dict], *,
                 endpoint: Optional[str] = None,
                 suspect_after_s: float = 1.0,
                 dead_after_s: float = 8.0,
                 lost_after_s: float = 30.0,
                 max_inflight_per_worker: Optional[int] = None,
                 handoff_timeout_s: float = 5.0,
                 handoff_max_attempts: int = 2,
                 handoff_backoff_s: float = 0.25,
                 clock=None, python: Optional[str] = None,
                 stderr_dir: Optional[str] = None):
        self.endpoint = endpoint or f"127.0.0.1:{free_port()}"
        self.store = bind_store(self.endpoint)
        self.session_base = uuid.uuid4().hex[:8]
        self.suspect_after_s = float(suspect_after_s)
        self.dead_after_s = float(dead_after_s)
        self.lost_after_s = float(lost_after_s)
        self.max_inflight_per_worker = max_inflight_per_worker
        self.handoff_timeout_s = float(handoff_timeout_s)
        self.handoff_max_attempts = int(handoff_max_attempts)
        self.handoff_backoff_s = float(handoff_backoff_s)
        self._clock = clock if clock is not None else time.monotonic
        self._python = python
        self.stderr_dir = stderr_dir
        self.workers: Dict[str, WorkerProc] = {}
        self._base_specs: Dict[str, dict] = {}
        for name, spec in worker_specs.items():
            spec = dict(spec)
            spec["endpoint"] = self.endpoint
            spec["session_base"] = self.session_base
            self._base_specs[name] = spec
            self.workers[name] = self._spawn(name, spec, generation=0)

        self._rid_counter = 0
        self.handles: Dict[int, object] = {}       # rid -> FleetHandle
        self._records: Dict[int, dict] = {}        # rid -> full record
        self._assign: Dict[int, str] = {}          # rid -> worker name
        self._deadline_at: Dict[int, float] = {}   # rid -> host deadline
        self._pending: Dict[int, Dict[int, int]] = {}   # out-of-order
        self._parked: List[Tuple[float, dict]] = []
        # workers that REJECTED a request (deterministic geometry
        # refusal): never re-land it there — with every healthy worker
        # excluded the request is finalized "lost", not looped forever
        self._excluded: Dict[int, set] = {}
        # ---- KV handoff state machine (ISSUE 18) ----
        # pull_id -> {rid, donor, target, phase, deadline, attempts,
        #             tokens, num_chunks, relayed, rec}; the request
        # stays ASSIGNED to the donor until placement so the normal
        # evacuation machinery parks it if the donor dies mid-stream
        self._handoffs: Dict[str, dict] = {}
        self._handoff_by_rid: Dict[int, str] = {}
        # rid -> worker names whose prefill_done was already acted on:
        # the donor re-ships it with heartbeats (healing a dropped
        # frame) and keeps doing so after a colocate fallback placed
        # the request back on it — without this, every heartbeat would
        # restart the handoff of a request that is already decoding
        self._handoff_done_seen: Dict[int, set] = {}
        self._pull_counter = 0
        self.counters: Dict[str, int] = {
            "requests_submitted": 0,
            "requests_finished": 0,
            "requests_migrated": 0,
            "requests_lost": 0,
            "catchup_tokens": 0,
            "tokens_delivered": 0,
            "funnel_duplicates": 0,
            "funnel_conflicts": 0,
            "events_buffered": 0,
            "worker_deaths": 0,
            "worker_kill9_observed": 0,
            "worker_hard_stalls": 0,
            "worker_drains": 0,
            "worker_restarts": 0,
            "worker_rejects": 0,
            "heartbeats": 0,
            "transport_errors": 0,
            # disaggregated prefill/decode (ISSUE 18)
            "handoffs_started": 0,      # prefill_done acted on
            "handoffs_completed": 0,    # target adopted shipped pages
            "handoffs_refetched": 0,    # placed WITHOUT pages: target
                                        # (or donor) re-prefilled
            "handoffs_colocated": 0,    # role-starved fallback to the
                                        # donor (colocate=True)
            "handoff_stalls": 0,        # phase deadlines that fired
            "kv_pages_shipped": 0,      # pages the targets adopted
        }

    # ---- plumbing --------------------------------------------------------
    def _spawn(self, name: str, spec: dict, *, generation: int):
        wp = WorkerProc(name, spec, self.store, python=self._python,
                        generation=generation)
        err = (os.path.join(self.stderr_dir, f"{name}_g{generation}.log")
               if self.stderr_dir else None)
        wp.spawn(stderr_path=err)
        return wp

    def _handle_cls(self):
        from .fleet import FleetHandle
        return FleetHandle

    def worker(self, name: str) -> WorkerProc:
        return self.workers[name]

    def _healthy(self) -> List[WorkerProc]:
        return [w for w in self.workers.values()
                if w.state in (WorkerState.SPAWNING, WorkerState.HEALTHY,
                               WorkerState.SUSPECT) and w.ready]

    def _assigned_to(self, name: str) -> List[int]:
        return [rid for rid, w in self._assign.items() if w == name]

    def has_work(self) -> bool:
        return bool(self._parked) or any(
            not h.finished for h in self.handles.values())

    # ---- admission -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               eos_token_id: Optional[int] = None,
               ttl_s: Optional[float] = None,
               adapter: Optional[str] = None):
        """Route one request to the least-loaded ready worker; returns
        its FleetHandle. The full record is retained host-side — it is
        the migration payload of last resort when a worker dies before
        ever shipping a snapshot.

        `adapter` (ISSUE 15) rides the request record: the worker's
        engine adopts it only with the adapter loaded (typed reject ->
        the existing park/exclude/re-land machinery finds a holder).
        Placement prefers workers whose SPEC declares the adapter in
        its `lora` block (factory-built registries are invisible
        host-side, so spec-less candidates stay eligible and the
        reject path remains the arbiter)."""
        from .errors import NoHealthyReplica
        from ..errors import EngineOverloaded
        candidates = self._healthy()
        if not candidates:
            raise NoHealthyReplica("no ready worker to accept work")
        # fresh work starts in its prefill phase: prefer prefill-
        # capable workers, falling back to anyone healthy (ISSUE 18)
        candidates = role_candidates(candidates, "prefill")
        if adapter is not None:
            declared = [w for w in candidates
                        if any(ad.get("name") == adapter
                               for ad in (self._base_specs.get(
                                   w.name, {}).get("lora", {})
                                   .get("adapters", ())))]
            if declared:
                candidates = declared

        def load_of(w):
            return w.reported_load + len(self._assigned_to(w.name))

        if self.max_inflight_per_worker is not None:
            candidates = [w for w in candidates
                          if load_of(w) < self.max_inflight_per_worker]
            if not candidates:
                raise EngineOverloaded(
                    "every worker is at max_inflight_per_worker",
                    max_queue_len=self.max_inflight_per_worker)
        target = min(candidates, key=load_of)
        self._rid_counter += 1
        rid = self._rid_counter
        rec = {"request_id": rid,
               "prompt_ids": [int(t) for t in prompt_ids],
               "output_ids": [],
               "max_new_tokens": int(max_new_tokens),
               "eos_token_id": (None if eos_token_id is None
                                else int(eos_token_id)),
               "num_preemptions": 0, "aborted": False,
               "adapter": adapter, "colocate": False,
               "deadline_remaining_s": (None if ttl_s is None
                                        else float(ttl_s))}
        handle = self._handle_cls()(rid, "_default")
        handle.submit_t = self._clock()
        self.handles[rid] = handle
        self._records[rid] = rec
        if ttl_s is not None:
            self._deadline_at[rid] = self._clock() + float(ttl_s)
        self._send_adopt(target, [rec])
        self.counters["requests_submitted"] += 1
        return handle

    def abort(self, request_id: int) -> bool:
        name = self._assign.get(request_id)
        rec = self._records.get(request_id)
        if rec is not None:
            rec["aborted"] = True
        for _, prec in self._parked:
            if prec["request_id"] == request_id:
                prec["aborted"] = True
                return True
        if name is not None and name in self.workers:
            try:
                self.workers[name].chan.send("abort", rid=int(request_id))
                return True
            except TransportError:
                self.counters["transport_errors"] += 1
        return False

    def _park(self, rid: int, base: Optional[dict] = None):
        """Park one request for re-landing, from the freshest truth:
        the record's resume point is the longest funnel-verified token
        prefix, and the remaining deadline is recomputed from the
        request's ORIGINAL host-side deadline — every park path (crash
        evacuation, worker reject, transport failure) must charge time
        already spent against the client's TTL, never re-grant it."""
        handle = self.handles.get(rid)
        if handle is None or handle.finished:
            return
        rec = dict(base if base is not None else self._records[rid])
        rec["output_ids"] = [int(t) for t in handle.tokens]
        rec["aborted"] = bool(self._records[rid].get("aborted"))
        now = self._clock()
        dl = self._deadline_at.get(rid)
        if dl is not None:
            rec["deadline_remaining_s"] = float(dl - now)
        self._parked.append((now, rec))

    def _send_adopt(self, worker: WorkerProc, recs: List[dict]) -> bool:
        """Adopt `recs` on `worker`; a transport failure parks them
        instead (the pump re-lands parked work — never an orphaned
        handle, never an exception through a caller's submit loop)."""
        try:
            worker.chan.send("adopt", recs=recs)
        except TransportError:
            self.counters["transport_errors"] += 1
            for rec in recs:
                self._park(rec["request_id"], rec)
            return False
        for rec in recs:
            self._assign[rec["request_id"]] = worker.name
        return True

    # ---- exactly-once funnel ---------------------------------------------
    def _deliver(self, handle, tok: int):
        handle._deliver(tok)
        now = self._clock()
        if handle.first_token_t is None:
            handle.first_token_t = now
        handle.token_ts.append(now)
        self.counters["tokens_delivered"] += 1

    def _funnel(self, rid: int, idx: int, tok: int):
        """Deliver exactly once, in order: duplicates discard by index
        (value-checked), gaps buffer until the prefix lands (a dropped
        event's tokens arrive via the next snapshot's catch-up)."""
        handle = self.handles.get(rid)
        if handle is None or handle.finished:
            return
        n = len(handle.tokens)
        if idx < n:
            if handle.tokens[idx] != tok:
                self.counters["funnel_conflicts"] += 1
            else:
                self.counters["funnel_duplicates"] += 1
            return
        if idx > n:
            self._pending.setdefault(rid, {})[idx] = tok
            self.counters["events_buffered"] += 1
            return
        self._deliver(handle, tok)
        pend = self._pending.get(rid)
        while pend:
            nxt = pend.pop(len(handle.tokens), None)
            if nxt is None:
                break
            self._deliver(handle, nxt)
        if not pend and rid in self._pending:
            self._pending.pop(rid, None)

    def _catch_up(self, handle, output_ids):
        """Deliver the verified suffix a snapshot knows and the funnel
        has not seen (the PR-7 catch-up rule, now also the heal for
        dropped event frames)."""
        for i in range(len(handle.tokens), len(output_ids)):
            self._deliver(handle, int(output_ids[i]))
            self.counters["catchup_tokens"] += 1
        pend = self._pending.pop(handle.request_id, None)
        if pend:
            for idx in sorted(pend):
                self._funnel(handle.request_id, idx, pend[idx])

    def _finalize(self, rid: int, reason: str):
        handle = self.handles.get(rid)
        self._assign.pop(rid, None)
        self._pending.pop(rid, None)
        self._deadline_at.pop(rid, None)
        self._excluded.pop(rid, None)
        self._handoff_done_seen.pop(rid, None)
        pid = self._handoff_by_rid.pop(rid, None)
        if pid is not None:
            self._drop_handoff(self._handoffs.get(pid))
        if handle is None or handle.finished:
            return
        handle.finish_t = self._clock()
        handle._finish(reason)
        self.counters["requests_lost" if reason == "lost"
                      else "requests_finished"] += 1

    # ---- KV handoff state machine (ISSUE 18) -----------------------------
    def _live_worker(self, name: Optional[str]) -> Optional[WorkerProc]:
        w = self.workers.get(name)
        if w is None or w.state in (WorkerState.DEAD,
                                    WorkerState.STOPPED):
            return None
        return w

    def _decode_target(self, rid: int,
                       exclude=()) -> Optional[WorkerProc]:
        """Least-loaded healthy decode-CAPABLE worker for `rid`, or
        None. Strict (no role fallback): the caller owns the degraded
        path (colocate on the donor), which is cheaper than landing
        decode work on a foreign prefill worker with a cold cache."""
        cands = [w for w in self._healthy()
                 if w.role in ("decode", "both")
                 and w.name not in exclude
                 and w.name not in self._excluded.get(rid, ())]
        if not cands:
            return None
        return min(cands, key=lambda w: (w.reported_load
                                         + len(self._assigned_to(w.name)),
                                         w.name))

    def _handoff_rec(self, rid: int) -> dict:
        """A placement-ready record for `rid`: resume point = the
        funnel-verified tokens, deadline recharged for time already
        spent (the `_park` discipline)."""
        handle = self.handles[rid]
        rec = dict(self._records[rid])
        rec["output_ids"] = [int(t) for t in handle.tokens]
        dl = self._deadline_at.get(rid)
        if dl is not None:
            rec["deadline_remaining_s"] = float(dl - self._clock())
        return rec

    def _on_prefill_done(self, worker: WorkerProc, payload: dict):
        """A prefill-role worker finished a request with reason
        "handoff": start (or ignore a re-delivery of) its KV handoff."""
        rid = int(payload.get("rid", -1))
        handle = self.handles.get(rid)
        if handle is None or handle.finished:
            return
        if self._assign.get(rid) != worker.name:
            return      # stale frame from a previous landing
        seen = self._handoff_done_seen.setdefault(rid, set())
        if worker.name in seen:
            return      # heartbeat re-delivery: already acted on
        seen.add(worker.name)
        self._catch_up(handle, payload.get("output_ids", []))
        self.counters["handoffs_started"] += 1
        rec = self._handoff_rec(rid)
        prefix_len = int(payload.get("prefix_len", 0))
        tokens = (rec["prompt_ids"] + rec["output_ids"])[:prefix_len]
        target = self._decode_target(rid, exclude={worker.name})
        if target is None:
            # role-starved: degrade to co-located execution on the
            # donor — its radix still holds the prefix, so the
            # re-prefill is a cache hit, not shed work
            self.counters["handoffs_colocated"] += 1
            rec["colocate"] = True
            self._records[rid]["colocate"] = True
            self._send_adopt(worker, [rec])
            return
        if not tokens or rec.get("adapter"):
            # nothing pullable (zero donated pages, or an adapter'd
            # request whose radix key the raw-token pull cannot
            # match): place pageless, the target re-prefills
            self.counters["handoffs_refetched"] += 1
            self._send_adopt(target, [rec])
            handle.migrations += 1
            return
        self._start_pull(rid, worker.name, target.name, tokens, rec)

    def _start_pull(self, rid: int, donor: str, target: str,
                    tokens, rec: dict, attempts: int = 1) -> dict:
        self._pull_counter += 1
        pull_id = f"ho{self._pull_counter}"
        entry = {"pull_id": pull_id, "rid": rid, "donor": donor,
                 "target": target, "phase": "pulling",
                 "deadline": self._clock() + self.handoff_timeout_s,
                 "retry_at": 0.0, "attempts": int(attempts),
                 "tokens": [int(t) for t in tokens],
                 "num_chunks": None, "relayed": 0, "rec": rec}
        self._handoffs[pull_id] = entry
        self._handoff_by_rid[rid] = pull_id
        try:
            self.workers[donor].chan.send("kv_pull", pull_id=pull_id,
                                          tokens=entry["tokens"])
        except TransportError:
            self.counters["transport_errors"] += 1
            # keep the entry: the phase deadline drives the retry
        return entry

    def _drop_handoff(self, entry: Optional[dict], *,
                      abort_target: bool = True):
        """Forget a handoff; optionally tell the target to drop its
        intake buffer (host-side dicts only — no pages allocate before
        adoption, so nothing can leak either way)."""
        if entry is None:
            return
        self._handoffs.pop(entry["pull_id"], None)
        if self._handoff_by_rid.get(entry["rid"]) == entry["pull_id"]:
            self._handoff_by_rid.pop(entry["rid"], None)
        if abort_target:
            target = self._live_worker(entry["target"])
            if target is not None:
                try:
                    target.chan.send("kv_abort",
                                     pull_id=entry["pull_id"])
                except TransportError:
                    self.counters["transport_errors"] += 1

    def _relay_to_target(self, entry: dict, msg: dict) -> bool:
        target = self._live_worker(entry["target"])
        if target is None:
            return False
        try:
            target.chan.relay(msg)
            return True
        except TransportError:
            self.counters["transport_errors"] += 1
            return False

    def _on_handoff_frame(self, worker: WorkerProc, mtype: str,
                          msg: dict):
        payload = msg.get("payload", {})
        entry = self._handoffs.get(payload.get("pull_id"))
        if entry is None:
            return          # late frame of an aborted/finished pull
        if entry["phase"] == "backoff":
            return          # stream already written off; retry pending
        if mtype in ("kv_prefix", "kv_page"):
            if worker.name != entry["donor"]:
                return
            if mtype == "kv_prefix":
                entry["num_chunks"] = int(payload.get("num_chunks", 0))
                matched = [int(t) for t in payload.get("tokens", [])]
                if matched:
                    entry["tokens"] = matched
                self._relay_to_target(entry, msg)
            else:
                if faults.fire(FAULT_HANDOFF_STALL) is not None:
                    return      # frame eaten: the stream wedges and
                                # the phase deadline must recover
                self._relay_to_target(entry, msg)
                entry["relayed"] += 1
            # progress re-arms the phase deadline
            entry["deadline"] = self._clock() + self.handoff_timeout_s
            if entry["num_chunks"] is not None:
                entry["phase"] = ("adopt_wait"
                                  if entry["relayed"] >= entry["num_chunks"]
                                  else "streaming")
        elif mtype == "kv_adopted":
            if worker.name != entry["target"]:
                return
            adopted = int(payload.get("adopted_pages", 0))
            self.counters["kv_pages_shipped"] += adopted
            if adopted > 0:
                self.counters["handoffs_completed"] += 1
                # phase 4, prefill-side release (fire-and-forget): the
                # shipped prefix becomes the donor's coldest eviction
                # victim instead of squatting on its pool
                donor = self._live_worker(entry["donor"])
                if donor is not None:
                    try:
                        donor.chan.send("kv_release",
                                        tokens=entry["tokens"])
                    except TransportError:
                        self.counters["transport_errors"] += 1
            else:
                # the target adopted nothing (dry pool / reassembly
                # gap / CRC): it re-prefills from its own state
                self.counters["handoffs_refetched"] += 1
            self._place_handoff(entry)

    def _place_handoff(self, entry: dict):
        """Adopt the request on its decode target (pull resolved —
        with pages or without). Post-placement failures are the
        standard machinery's business: the rid is assigned to the
        target from here on."""
        self._drop_handoff(entry, abort_target=False)
        rid = entry["rid"]
        handle = self.handles.get(rid)
        if handle is None or handle.finished:
            return
        rec = self._handoff_rec(rid)
        rec["colocate"] = entry["rec"].get("colocate", False)
        target = self._live_worker(entry["target"])
        if target is None or not target.ready:
            self._assign.pop(rid, None)
            self._park(rid, rec)
            return
        self._send_adopt(target, [rec])
        handle.migrations += 1

    def _check_handoffs(self):
        """Drive every in-flight handoff's deadlines and failure
        transitions (one pump iteration's worth)."""
        now = self._clock()
        for entry in list(self._handoffs.values()):
            rid = entry["rid"]
            handle = self.handles.get(rid)
            if handle is None or handle.finished:
                self._drop_handoff(entry)
                continue
            if self._assign.get(rid) != entry["donor"]:
                # the donor died and evacuation parked the rid under
                # us: the park/re-land machinery owns it now (role-
                # aware; the decode side re-prefills — a refetch)
                self.counters["handoffs_refetched"] += 1
                self._drop_handoff(entry)
                continue
            if self._live_worker(entry["target"]) is None:
                # target died pre-placement: re-route to a survivor
                self._drop_handoff(entry, abort_target=False)
                self._reroute(entry, now)
                continue
            if entry["phase"] == "backoff":
                if now >= entry["retry_at"]:
                    self._drop_handoff(entry, abort_target=False)
                    self._reroute(entry, now)
                continue
            if now < entry["deadline"]:
                continue
            # a phase wedged (stalled stream, lost pull, mute target):
            # abort the target's intake, then capped backoff + re-pull
            # while attempts remain, else give the pages up
            self.counters["handoff_stalls"] += 1
            target = self._live_worker(entry["target"])
            if target is not None:
                try:
                    target.chan.send("kv_abort",
                                     pull_id=entry["pull_id"])
                except TransportError:
                    self.counters["transport_errors"] += 1
            if entry["attempts"] < self.handoff_max_attempts:
                # re-key NOW so straggler frames of the written-off
                # stream can't resurrect the entry; send after backoff
                self._handoffs.pop(entry["pull_id"], None)
                self._pull_counter += 1
                entry["pull_id"] = f"ho{self._pull_counter}"
                self._handoffs[entry["pull_id"]] = entry
                self._handoff_by_rid[rid] = entry["pull_id"]
                entry["phase"] = "backoff"
                entry["retry_at"] = now + self.handoff_backoff_s * (
                    2 ** (entry["attempts"] - 1))
                entry["num_chunks"] = None
                entry["relayed"] = 0
            else:
                self.counters["handoffs_refetched"] += 1
                self._place_handoff(entry)

    def _reroute(self, entry: dict, now: float):
        """Continue a handoff whose stream was written off (backoff
        expiry or target death): fresh pull to a fresh target, pageless
        placement when attempts are spent, colocate when role-starved."""
        rid = entry["rid"]
        handle = self.handles.get(rid)
        if handle is None or handle.finished:
            return
        donor = self._live_worker(entry["donor"])
        target = self._decode_target(rid, exclude={entry["donor"]})
        rec = self._handoff_rec(rid)
        rec["colocate"] = entry["rec"].get("colocate", False)
        if target is None:
            if donor is not None:
                self.counters["handoffs_colocated"] += 1
                rec["colocate"] = True
                self._records[rid]["colocate"] = True
                self._send_adopt(donor, [rec])
            # donor dead too: leave the rid assigned — the donor's
            # evacuation parks it and the normal machinery re-lands
            return
        if donor is None or entry["attempts"] >= self.handoff_max_attempts:
            # no donor to pull from (or attempts spent): pageless
            # placement, the target re-prefills bit-identically
            self.counters["handoffs_refetched"] += 1
            self._send_adopt(target, [rec])
            handle.migrations += 1
            return
        self._start_pull(rid, entry["donor"], target.name,
                         entry["tokens"], rec,
                         attempts=entry["attempts"] + 1)

    # ---- message processing ----------------------------------------------
    def _process(self, worker: WorkerProc, msg: dict):
        mtype = msg.get("type")
        payload = msg.get("payload", {})
        if mtype == "ready":
            worker.ready = True
            if worker.state is WorkerState.SPAWNING:
                worker.state = WorkerState.HEALTHY
            worker.last_beat_host_t = self._clock()
        elif mtype == "heartbeat":
            worker.last_beat_host_t = self._clock()
            worker.last_beat = payload
            worker.reported_load = int(payload.get("load", 0))
            worker.beats += 1
            worker.fired = dict(payload.get("fired", {}))
            # a heartbeat implies ready — heals a dropped ready frame
            worker.ready = True
            if worker.state in (WorkerState.SPAWNING,
                                WorkerState.SUSPECT):
                worker.state = WorkerState.HEALTHY
            snap = payload.get("snapshot")
            if snap is not None:
                worker.last_snapshot = snap
                # the heartbeat snapshot is the authoritative healer
                # for dropped/stalled EVENT frames: catch the funnel up
                # to every verified prefix this worker reports for
                # requests it still owns
                for rec in snap.get("requests", []):
                    rid = int(rec.get("request_id", -1))
                    if self._assign.get(rid) != worker.name:
                        continue
                    handle = self.handles.get(rid)
                    if handle is not None and not handle.finished:
                        self._catch_up(handle,
                                       rec.get("output_ids", []))
            # ... and re-shipped finish records heal dropped FINISH
            # frames (idempotent: finalize checks handle.finished)
            for fin in payload.get("recent_finished", []):
                rid = int(fin.get("rid", -1))
                handle = self.handles.get(rid)
                if handle is not None and not handle.finished:
                    self._catch_up(handle, fin.get("output_ids", []))
                    self._finalize(rid, fin.get("reason", "stop"))
            # ... and re-shipped handoff records heal dropped
            # prefill_done frames (idempotent per donor via
            # _handoff_done_seen)
            for ho in payload.get("recent_handoffs", []):
                self._on_prefill_done(worker, ho)
            self.counters["heartbeats"] += 1
        elif mtype == "events":
            worker.last_beat_host_t = self._clock()
            for rid, idx, tok in payload.get("ev", []):
                self._funnel(int(rid), int(idx), int(tok))
        elif mtype == "finish":
            rid = int(payload["rid"])
            handle = self.handles.get(rid)
            if handle is not None and not handle.finished:
                self._catch_up(handle, payload.get("output_ids", []))
            self._finalize(rid, payload.get("reason", "stop"))
        elif mtype == "prefill_done":
            worker.last_beat_host_t = self._clock()
            self._on_prefill_done(worker, payload)
        elif mtype in ("kv_prefix", "kv_page", "kv_adopted"):
            worker.last_beat_host_t = self._clock()
            self._on_handoff_frame(worker, mtype, msg)
        elif mtype == "adopted":
            worker.last_beat_host_t = self._clock()
        elif mtype == "pong":
            # the ping round-trip's answer: proof the worker LOOP is
            # alive (not just the process), so it counts as liveness
            worker.last_beat_host_t = self._clock()
            worker.pongs += 1
        elif mtype == "stats":
            worker.last_stats = payload
        elif mtype == "reject":
            self.counters["worker_rejects"] += 1
            for rid in payload.get("rids", []):
                rid = int(rid)
                if self._assign.get(rid) != worker.name:
                    # stale or DUPLICATED reject frame: the request was
                    # already re-parked/re-landed — parking it again
                    # would have two workers generating the same rid
                    continue
                self._assign.pop(rid, None)
                self._excluded.setdefault(rid, set()).add(worker.name)
                self._park(rid)
        elif mtype == "snapshot":
            # counts as liveness: the worker may spend seconds in its
            # post-snapshot compile-cache save with no heartbeats
            worker.last_beat_host_t = self._clock()
            if payload.get("final"):
                self._evacuate(worker, payload.get("snapshot"))
        elif mtype == "bye":
            worker.fired.update(payload.get("fired", {}))
            if worker.state is not WorkerState.DEAD:
                worker.state = WorkerState.STOPPED
        elif mtype == "failed":
            self._mark_dead(worker, snapshot=payload.get("snapshot"))

    # ---- failure handling ------------------------------------------------
    def _mark_dead(self, worker: WorkerProc, snapshot: Optional[dict]
                   = None):
        if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
            return
        # drain whatever the worker managed to send before dying —
        # events/finishes/a final snapshot are sequenced AHEAD of the
        # death in its mailbox and must not be lost with it (a bye in
        # there resolves this as a graceful stop instead)
        if not worker._draining_mailbox:
            worker._draining_mailbox = True
            try:
                msgs = worker.chan.recv_all()
            except TransportError:
                self.counters["transport_errors"] += 1
                msgs = []
            for msg in msgs:
                self._process(worker, msg)
            worker._draining_mailbox = False
            if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
                return
        worker.state = WorkerState.DEAD
        self.counters["worker_deaths"] += 1
        rc = worker.poll()
        try:
            import signal as _signal
            if rc is not None and -rc == int(_signal.SIGKILL):
                self.counters["worker_kill9_observed"] += 1
        except Exception:                                 # noqa: BLE001
            pass
        worker.kill()
        self._evacuate(worker,
                       snapshot if snapshot is not None
                       else worker.last_snapshot)

    def _evacuate(self, worker: WorkerProc, snapshot: Optional[dict]):
        """Park every request assigned to `worker` for re-landing. The
        migration record merges the last shipped snapshot with what the
        funnel verified: snapshot tokens the stream never saw are
        delivered as catch-up, then the record's resume point is the
        longest delivered prefix (regenerated overlap dedups by
        index)."""
        recs = {}
        if snapshot:
            try:
                from ..engine import check_snapshot_version
                check_snapshot_version(snapshot)
                recs = {r["request_id"]: r
                        for r in snapshot.get("requests", [])}
            except Exception:                             # noqa: BLE001
                recs = {}
        for rid in self._assigned_to(worker.name):
            handle = self.handles.get(rid)
            if handle is None or handle.finished:
                self._assign.pop(rid, None)
                continue
            rec = recs.get(rid)
            if rec is not None:
                self._catch_up(handle, rec.get("output_ids", []))
            self._assign.pop(rid, None)
            self._park(rid, rec)

    def _process_parked(self):
        if not self._parked:
            return 0
        healthy = self._healthy()
        if not healthy:
            # no landing spot RIGHT NOW is not loss: a rolling restart
            # leaves a window with every worker stopped before its
            # successor is ready. Only work parked past the grace
            # period with still nobody to adopt it is finalized lost.
            kept = []
            for t0, rec in self._parked:
                if self._clock() - t0 > self.lost_after_s:
                    self._finalize(rec["request_id"], "lost")
                else:
                    kept.append((t0, rec))
            self._parked = kept
            return 0
        parked, self._parked = self._parked, []
        landed = 0
        for t0, rec in parked:
            rid = rec["request_id"]
            handle = self.handles.get(rid)
            if handle is None or handle.finished:
                continue
            if len(rec["output_ids"]) >= rec["max_new_tokens"]:
                # everything was already generated+delivered before the
                # failure; nothing to resume
                self._finalize(rid, "length")
                continue
            candidates = [w for w in healthy
                          if w.name not in self._excluded.get(rid, ())]
            if not candidates:
                self._finalize(rid, "lost")
                continue
            # role-aware re-landing (ISSUE 18): a record with output
            # is past its prefill phase and belongs on a decode-
            # capable worker; a fresh one belongs on prefill-capable.
            # role_candidates falls back to everyone when starved —
            # landing decode work on a prefill-role worker then
            # requires colocate, or its engine would hand it off again
            phase = "decode" if rec["output_ids"] else "prefill"
            candidates = role_candidates(candidates, phase)
            target = min(candidates, key=lambda w: (w.reported_load
                         + len(self._assigned_to(w.name))))
            if phase == "decode" and target.role == "prefill":
                rec["colocate"] = True
                if rid in self._records:
                    self._records[rid]["colocate"] = True
            if not self._send_adopt(target, [rec]):
                continue     # parked again; retried next pump
            handle.migrations += 1
            self.counters["requests_migrated"] += 1
            landed += 1
        return landed

    # ---- the pump --------------------------------------------------------
    def pump(self) -> int:
        """One supervisor iteration: drain every worker's mailbox, run
        the liveness ladder, re-land parked work. Returns messages
        processed."""
        n = 0
        for worker in list(self.workers.values()):
            if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
                continue
            try:
                msgs = worker.chan.recv_all()
            except TransportError:
                self.counters["transport_errors"] += 1
                msgs = []
            for msg in msgs:
                self._process(worker, msg)
                n += 1
        self._check_liveness()
        self._check_handoffs()
        self._process_parked()
        return n

    def _check_liveness(self):
        now = self._clock()
        for worker in list(self.workers.values()):
            if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
                continue
            rc = worker.poll()
            if rc is not None:
                if worker.state is WorkerState.DRAINING and rc == 0:
                    # graceful exit raced the bye message; final
                    # snapshot/bye (already sent) will drain next pump
                    continue
                self._mark_dead(worker)
                continue
            if worker.last_beat_host_t is None:
                continue
            gap = now - worker.last_beat_host_t
            if gap > self.dead_after_s:
                # permanently stalled (wedged transport/device): kill
                # what's left and adopt from the last snapshot
                self.counters["worker_hard_stalls"] += 1
                self._mark_dead(worker)
            elif gap > self.suspect_after_s and \
                    worker.state is WorkerState.HEALTHY:
                worker.state = WorkerState.SUSPECT

    def heartbeat_gap_s(self, name: str) -> Optional[float]:
        w = self.workers[name]
        if w.last_beat_host_t is None:
            return None
        return max(0.0, self._clock() - w.last_beat_host_t)

    # ---- deliberate lifecycle --------------------------------------------
    def drain(self, name: str) -> bool:
        """Ask one worker to snapshot-and-exit gracefully; its final
        snapshot parks and re-lands through the normal pump."""
        worker = self.workers[name]
        if worker.state not in (WorkerState.HEALTHY, WorkerState.SUSPECT,
                                WorkerState.SPAWNING):
            return False
        worker.state = WorkerState.DRAINING
        self.counters["worker_drains"] += 1
        try:
            worker.chan.send("drain")
        except TransportError:
            self.counters["transport_errors"] += 1
            self._mark_dead(worker)
        return True

    def respawn(self, name: str) -> WorkerProc:
        """Replace a STOPPED/DEAD worker with a fresh process (next
        channel generation). With a shared compile_cache_dir the
        successor loads its programs from disk instead of recompiling
        the bucket grid."""
        old = self.workers[name]
        if old.state not in (WorkerState.DEAD, WorkerState.STOPPED):
            raise RuntimeError(f"worker {name} is {old.state.value}; "
                               f"drain it first")
        old.kill()
        old.cleanup()
        old.chan.purge()     # the dead generation's frames and heads
        wp = self._spawn(name, self._base_specs[name],
                         generation=old.generation + 1)
        self.workers[name] = wp
        self.counters["worker_restarts"] += 1
        return wp

    def rolling_restart(self, name: str, *, timeout_s: float = 60.0):
        """drain -> wait for the graceful exit -> respawn. Parked work
        re-lands on the next pump (on the successor once it is ready,
        or on any other healthy worker meanwhile)."""
        self.drain(name)
        deadline = time.monotonic() + timeout_s
        while self.workers[name].state not in (WorkerState.STOPPED,
                                               WorkerState.DEAD):
            self.pump()
            if time.monotonic() > deadline:
                self._mark_dead(self.workers[name])
                break
            time.sleep(5e-3)
        return self.respawn(name)

    # ---- drive to completion ---------------------------------------------
    def run(self, timeout_s: float = 300.0) -> Dict[int, List[int]]:
        """Pump until every tracked handle finishes (or timeout —
        raises). Returns {rid: tokens} for every handle tracked at the
        call."""
        tracked = dict(self.handles)
        deadline = time.monotonic() + float(timeout_s)
        while any(not h.finished for h in tracked.values()):
            n = self.pump()
            if time.monotonic() > deadline:
                livef = [rid for rid, h in tracked.items()
                         if not h.finished]
                raise RuntimeError(
                    f"ProcessFleet failed to drain: {len(livef)} "
                    f"requests live after {timeout_s}s "
                    f"(e.g. {livef[:8]}); states="
                    f"{ {w.name: w.state.value for w in self.workers.values()} }")
            if not n:
                time.sleep(2e-3)
        return {rid: list(h.tokens) for rid, h in tracked.items()}

    def shutdown(self, timeout_s: float = 10.0):
        """Graceful stop of every live worker; stragglers are killed.
        Mailbox keys the dead peers never consumed are purged and the
        supervisor's store is released from the process-wide registry
        — a long-lived process running fleets sequentially must not
        accumulate listening stores and orphaned frames."""
        for w in self.workers.values():
            if w.state in (WorkerState.HEALTHY, WorkerState.SUSPECT,
                           WorkerState.SPAWNING, WorkerState.DRAINING):
                try:
                    w.chan.send("shutdown")
                except TransportError:
                    pass
        deadline = time.monotonic() + timeout_s
        for w in self.workers.values():
            w.wait(timeout=max(0.1, deadline - time.monotonic()))
            w.kill()
            w.cleanup()
            w.chan.purge()
        from ...distributed.env import release_store
        release_store(self.endpoint)
        self.store = None

    def request_stats(self, name: str, *, reset_prefix_cache: bool =
                      False, timeout_s: float = 10.0) -> Optional[dict]:
        """Round-trip the reclamation probe on one live worker (None on
        timeout / non-live worker)."""
        worker = self.workers[name]
        if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
            return None
        worker.last_stats = None
        try:
            worker.chan.send("stats",
                             reset_prefix_cache=bool(reset_prefix_cache))
        except TransportError:
            self.counters["transport_errors"] += 1
            return None
        deadline = time.monotonic() + timeout_s
        while worker.last_stats is None and \
                time.monotonic() < deadline:
            self.pump()
            time.sleep(5e-3)
        return worker.last_stats

    def ping(self, name: str, *, timeout_s: float = 10.0) -> bool:
        """Explicit liveness round-trip on one worker: send `ping`,
        pump until its `pong` lands (which also refreshes the
        heartbeat clock). Heartbeats prove liveness passively every
        interval; ping answers "is the LOOP responsive right now"
        on demand — e.g. before routing a large adopt batch at a
        worker whose last beat is aging."""
        worker = self.workers[name]
        if worker.state in (WorkerState.DEAD, WorkerState.STOPPED):
            return False
        before = worker.pongs
        try:
            worker.chan.send("ping")
        except TransportError:
            self.counters["transport_errors"] += 1
            return False
        deadline = time.monotonic() + timeout_s
        while worker.pongs == before and time.monotonic() < deadline:
            self.pump()
            time.sleep(5e-3)
        return worker.pongs > before

    # ---- observability ----------------------------------------------------
    def fired_counts(self) -> Dict[str, int]:
        """Union of worker-reported fault firings (latest per worker) —
        the soak's proof that armed worker-side points landed."""
        out: Dict[str, int] = {}
        for w in self.workers.values():
            for k, v in w.fired.items():
                out[k] = out.get(k, 0) + int(v)
        return out

    def summary(self) -> dict:
        snap = {f"fleet_{k}": v for k, v in self.counters.items()}
        snap["worker_states"] = {w.name: w.state.value
                                 for w in self.workers.values()}
        snap["worker_roles"] = {w.name: w.role
                                for w in self.workers.values()}
        return snap

    def prometheus_text(self, *, prefix: str = "paddle_serving") -> str:
        """The cross-process fleet as one Prometheus scrape: supervisor
        counters, then per-WORKER labeled series — liveness, heartbeat
        gap/age (the rolling-restart visibility criterion), reported
        load, and the worker's own engine counters from its last
        heartbeat under a `worker="<name>"` label (mirroring the
        in-process fleet's `replica` labels; OBSERVABILITY.md)."""
        from ..exposition import (metric_name, prometheus_lines,
                                  sanitize_label_value)
        lines = prometheus_lines(
            {f"fleet_{k}": v for k, v in self.counters.items()},
            counter_keys={f"fleet_{k}" for k in self.counters},
            prefix=prefix)
        for w in self.workers.values():
            lab = f'{{worker="{sanitize_label_value(w.name)}"}}'
            up = int(w.state in (WorkerState.HEALTHY, WorkerState.SUSPECT,
                                 WorkerState.DRAINING))
            lines.append(
                f'{metric_name(prefix, "worker_up")}{lab} {up}')
            gap = self.heartbeat_gap_s(w.name)
            if gap is not None:
                lines.append(
                    f'{metric_name(prefix, "worker_heartbeat_gap_seconds")}'
                    f'{lab} {round(gap, 6)}')
            lines.append(
                f'{metric_name(prefix, "worker_reported_load")}{lab} '
                f'{w.reported_load}')
            lines.append(
                f'{metric_name(prefix, "worker_generation")}{lab} '
                f'{w.generation}')
            # role as an info-style series (value 1, role in the
            # label): adding a label to the existing series would
            # break every scrape joining on {worker=...} alone
            lines.append(
                f'{metric_name(prefix, "worker_role")}'
                f'{{worker="{sanitize_label_value(w.name)}",'
                f'role="{sanitize_label_value(w.role)}"}} 1')
            if w.last_beat:
                counters = w.last_beat.get("counters", {})
                lines.extend(prometheus_lines(
                    counters, counter_keys=set(counters), prefix=prefix,
                    labels={"worker": w.name}, emit_type=False))
        return "\n".join(lines) + "\n"
