"""HTTP/SSE front door over a FleetServer (ISSUE 14).

A wire-level front end so clients outside this process can reach the
fleet — dependency-free (asyncio streams + hand-rolled HTTP/1.1; no
aiohttp in the container) and deliberately small: the protocol work
(streaming, failover, admission, metrics) all lives below, this module
only translates it onto sockets.

Routes:

* ``POST /v1/completions`` — body ``{"prompt_ids": [...],
  "max_new_tokens": N, "stream": true|false, "eos_token_id": ...,
  "ttl_s": ..., "tenant": ..., "adapter": ...,
  "ttft_slo_s": ..., "tpot_slo_s": ...}``.
  With ``stream`` (default true) the response is Server-Sent Events:
  one ``data: {token event}`` per token delta from the existing
  `TokenStream`, then one ``data: {finish event}``, then ``data:
  [DONE]`` — the OpenAI-style shape at token-id level. Without it, one
  JSON body ``{"request_id", "tokens", "finish_reason"}``. Typed
  admission sheds map to status codes: 429 (`EngineOverloaded` /
  tenant throttle / SLO shed), 503 (`NoHealthyReplica`),
  404 (`AdapterNotLoaded` — the named LoRA adapter is on no healthy
  replica, ISSUE 15), 400 for bad payloads.
* ``GET /metrics`` — the existing `FleetServer.metrics_text()`
  Prometheus body (merged fleet + per-replica labels).
* ``GET /healthz`` — JSON from replica heartbeats: per-replica state +
  heartbeat age on the fleet clock, 200 while any replica is healthy,
  503 otherwise.

Connection model: one asyncio task per connection on the same event
loop the replica stepping tasks share; SSE responses are
``Connection: close`` (no chunked framing needed). A client that
disconnects mid-stream closes its TokenStream — the request itself
keeps running (abort is an explicit API, not a hangup side effect).
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional, Tuple

__all__ = ["HttpFrontend"]

_MAX_HEADER = 64 * 1024
_MAX_BODY = 4 * 1024 * 1024


def _http_response(status: int, reason: str, body: bytes,
                   content_type: str = "application/json") -> bytes:
    return (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n").encode() + body


class HttpFrontend:
    """Serve a FleetServer over HTTP/SSE on (host, port).

    Use as an async context manager (starts the FleetServer too if it
    is not already running):

        async with FleetServer(fleet) as server, \\
                HttpFrontend(server, port=0) as front:
            ...  # front.port is the bound port
    """

    def __init__(self, server, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.server = server          # the FleetServer
        self.host = host
        self.port = int(port)         # 0 = ephemeral; real port after start
        self._srv: Optional[asyncio.AbstractServer] = None
        self.counters = {"requests": 0, "streams": 0, "errors": 0,
                         "bad_requests": 0, "sheds": 0}

    # ---- lifecycle -------------------------------------------------------
    async def start(self):
        if self._srv is not None:
            return self
        self._srv = await asyncio.start_server(
            self._serve_conn, self.host, self.port, limit=_MAX_HEADER)
        self.port = self._srv.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        if self._srv is not None:
            self._srv.close()
            await self._srv.wait_closed()
            self._srv = None

    async def __aenter__(self):
        return await self.start()

    async def __aexit__(self, *exc):
        await self.stop()

    # ---- request plumbing ------------------------------------------------
    @staticmethod
    async def _read_request(reader) -> Optional[Tuple[str, str, dict,
                                                      bytes]]:
        try:
            # the stream limit (start_server limit=_MAX_HEADER) bounds
            # the header block: oversized headers surface here as
            # LimitOverrunError and become a 400, not a silent close
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.LimitOverrunError:
            return None
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, path, _ = lines[0].split(" ", 2)
        except ValueError:
            return None
        headers = {}
        for ln in lines[1:]:
            if ":" in ln:
                k, v = ln.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError:
            return None     # malformed Content-Length: a 400, not a 500
        if length < 0 or length > _MAX_BODY:
            return None
        body = await reader.readexactly(length) if length else b""
        return method.upper(), path, headers, body

    async def _serve_conn(self, reader, writer):
        try:
            req = await self._read_request(reader)
            if req is None:
                self.counters["bad_requests"] += 1
                writer.write(_http_response(400, "Bad Request",
                                            b'{"error":"bad request"}'))
            else:
                method, path, _, body = req
                self.counters["requests"] += 1
                await self._route(method, path.split("?", 1)[0], body,
                                  writer)
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass                       # client went away; nothing owed
        except Exception:                                 # noqa: BLE001
            self.counters["errors"] += 1
            try:
                writer.write(_http_response(
                    500, "Internal Server Error",
                    b'{"error":"internal"}'))
                await writer.drain()
            except Exception:                             # noqa: BLE001
                pass
        finally:
            try:
                writer.close()
            except Exception:                             # noqa: BLE001
                pass

    async def _route(self, method: str, path: str, body: bytes, writer):
        if method == "GET" and path == "/metrics":
            text = self.server.metrics_text().encode()
            writer.write(_http_response(
                200, "OK", text,
                content_type="text/plain; version=0.0.4"))
        elif method == "GET" and path == "/healthz":
            writer.write(self._healthz())
        elif method == "POST" and path == "/v1/completions":
            await self._completions(body, writer)
        else:
            writer.write(_http_response(404, "Not Found",
                                        b'{"error":"not found"}'))

    # ---- endpoints -------------------------------------------------------
    def _healthz(self) -> bytes:
        from .replica import ReplicaState
        fleet = self.server.fleet
        now = fleet._clock()
        replicas = {
            r.name: {"state": r.state.value,
                     "heartbeat_age_s": round(max(
                         0.0, now - r.last_progress), 6),
                     "load": r.load}
            for r in fleet.replicas}
        healthy = any(r.state is ReplicaState.HEALTHY
                      for r in fleet.replicas)
        doc = {"status": "ok" if healthy else "unavailable",
               "replicas": replicas}
        return _http_response(200 if healthy else 503,
                              "OK" if healthy else "Service Unavailable",
                              json.dumps(doc).encode())

    async def _completions(self, body: bytes, writer):
        from ..errors import EngineOverloaded
        from ..lora.adapter import AdapterNotLoaded
        from .errors import NoHealthyReplica
        try:
            req = json.loads(body.decode("utf-8") or "{}")
            prompt_ids = [int(t) for t in req["prompt_ids"]]
            kw = {}
            for k in ("max_new_tokens", "eos_token_id", "ttl_s",
                      "tenant", "adapter", "ttft_slo_s", "tpot_slo_s"):
                if req.get(k) is not None:
                    kw[k] = req[k]
            stream_mode = bool(req.get("stream", True))
        except Exception:                                 # noqa: BLE001
            self.counters["bad_requests"] += 1
            writer.write(_http_response(
                400, "Bad Request",
                b'{"error":"body must be JSON with prompt_ids"}'))
            return
        try:
            stream = await self.server.submit(prompt_ids, **kw)
        except AdapterNotLoaded as e:
            # ISSUE 15: the named LoRA adapter is loaded on no healthy
            # replica — a resource the fleet does not currently hold
            self.counters["sheds"] += 1
            writer.write(_http_response(
                404, "Not Found",
                json.dumps({"error": type(e).__name__,
                            "detail": str(e)}).encode()))
            return
        except EngineOverloaded as e:
            self.counters["sheds"] += 1
            writer.write(_http_response(
                429, "Too Many Requests",
                json.dumps({"error": type(e).__name__,
                            "detail": str(e)}).encode()))
            return
        except NoHealthyReplica as e:
            self.counters["sheds"] += 1
            writer.write(_http_response(
                503, "Service Unavailable",
                json.dumps({"error": type(e).__name__,
                            "detail": str(e)}).encode()))
            return
        if not stream_mode:
            tokens, reason = await stream.collect()
            writer.write(_http_response(
                200, "OK",
                json.dumps({"request_id": stream.request_id,
                            "tokens": tokens,
                            "finish_reason": reason}).encode()))
            return
        self.counters["streams"] += 1
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        try:
            async for event in stream:
                writer.write(b"data: "
                             + json.dumps(event).encode() + b"\n\n")
                await writer.drain()
            writer.write(b"data: [DONE]\n\n")
        except (ConnectionError, asyncio.IncompleteReadError):
            raise
        except Exception:                                 # noqa: BLE001
            # the SSE preamble is already on the wire: a status line
            # appended mid-body would be protocol garbage, so an
            # unexpected failure ends the stream with a clean close
            # (counted) — never the outer handler's 500
            self.counters["errors"] += 1
        finally:
            # a gone client detaches its stream; the request lives on
            stream.close()
