"""Asyncio streaming front-end over a Fleet.

`FleetServer` drives every replica on ITS OWN stepping loop (one
asyncio task per replica — a dead replica's loop exits; the survivors
keep stepping and absorb its migrated work) plus a monitor task for
stall detection and parked-work pickup, and exposes the client-facing
streaming API:

    async with FleetServer(fleet) as server:
        stream = await server.submit(prompt_ids, max_new_tokens=16)
        async for event in stream:          # OpenAI-style event shapes
            ...  # {"type": "token", "token": t, "index": i, ...}
                 # then one {"type": "finish", "finish_reason": ...}

Events are token DELTAS followed by exactly one finish event — the
streamed shape of an OpenAI-style completions response (token-id
level; tokenization lives outside this repo). `TokenStream.collect()`
is the non-streaming convenience.

Concurrency model: everything runs on the event loop thread — engine
steps are synchronous calls from the replica tasks (an engine step on
CPU blocks the loop for its duration, which is fine for the in-process
replicas this serves), and handle listeners enqueue into per-stream
asyncio queues, so no locks are needed anywhere. Failover is inherited
wholesale from the Fleet: a crash inside `step_replica` parks and
re-lands work without the streaming layer noticing beyond the tokens
continuing to arrive — the zero-loss contract is the Fleet's, the
server just never drops an event.
"""
from __future__ import annotations

import asyncio
import traceback
import warnings
from typing import List, Optional, Tuple

from .fleet import Fleet, FleetHandle, finish_event, token_event
from .replica import ReplicaState

__all__ = ["FleetServer", "TokenStream"]


class TokenStream:
    """Async iterator over one request's events. Attaching replays any
    tokens delivered before the stream existed (e.g. a handle obtained
    synchronously and streamed later), then subscribes to the handle —
    both on the event-loop thread, so no event can fall in between."""

    def __init__(self, handle: FleetHandle):
        self.handle = handle
        self._q: asyncio.Queue = asyncio.Queue()
        self._done = False
        for i, tok in enumerate(handle.tokens):
            self._q.put_nowait(token_event(handle, tok, i))
        if handle.finished:
            self._q.put_nowait(finish_event(handle,
                                            handle.finish_reason))
        handle.subscribe(self._q.put_nowait)

    @property
    def request_id(self) -> int:
        return self.handle.request_id

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        if self._done:
            raise StopAsyncIteration
        event = await self._q.get()
        if event["type"] == "finish":
            self._done = True
        return event

    def close(self):
        """Detach from a LIVE handle early (an abandoned stream's queue
        would otherwise keep accumulating events until the request
        finishes; at finish the handle drops its listeners itself).
        A consumer already blocked in `__anext__` is woken with a
        synthetic finish event (`finish_reason="closed"`)."""
        self.handle.unsubscribe(self._q.put_nowait)
        if self._done:
            return
        self._done = True
        self._q.put_nowait(finish_event(self.handle, "closed"))

    async def collect(self) -> Tuple[List[int], Optional[str]]:
        """Drain the stream; returns (tokens, finish_reason)."""
        async for _ in self:
            pass
        return list(self.handle.tokens), self.handle.finish_reason


class FleetServer:
    """The asyncio shell: per-replica stepping tasks + health monitor
    over a synchronous Fleet. `idle_sleep_s` is how long an idle
    replica loop naps (0 still yields to the loop each step);
    `health_interval_s` paces stall detection and the parked-work
    sweep that keeps migration moving even if every replica loop has
    exited."""

    def __init__(self, fleet: Fleet, *, idle_sleep_s: float = 1e-3,
                 health_interval_s: float = 1e-2):
        self.fleet = fleet
        self.idle_sleep_s = float(idle_sleep_s)
        self.health_interval_s = float(health_interval_s)
        self._running = False
        self._tasks: List[asyncio.Task] = []
        # unexpected exceptions from the loop bodies: counted, first few
        # warned with tracebacks, loop kept ALIVE — a monitor that died
        # silently would stop stall detection and parked-work pickup
        # while the server kept accepting work
        self.loop_errors = 0

    def _on_loop_error(self, where: str):
        self.loop_errors += 1
        if self.loop_errors <= 3:
            warnings.warn(
                f"FleetServer {where} error (#{self.loop_errors}, "
                f"loop continues):\n{traceback.format_exc()}",
                RuntimeWarning, stacklevel=2)

    # ---- lifecycle -------------------------------------------------------
    async def start(self):
        if self._running:
            return
        self._running = True
        self._tasks = [asyncio.ensure_future(self._replica_loop(r))
                       for r in self.fleet.replicas]
        self._tasks.append(asyncio.ensure_future(self._monitor()))

    async def stop(self):
        self._running = False
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, *exc):
        await self.stop()

    # ---- the loops -------------------------------------------------------
    async def _replica_loop(self, replica):
        while self._running and replica.state is ReplicaState.HEALTHY:
            try:
                emitted = self.fleet.step_replica(replica)
                busy = bool(emitted) or replica.engine.has_work() \
                    or bool(self.fleet._parked)
            except asyncio.CancelledError:
                raise
            except Exception:                         # noqa: BLE001
                self._on_loop_error(f"replica_loop[{replica.name}]")
                busy = False
            await asyncio.sleep(0 if busy else self.idle_sleep_s)

    async def _monitor(self):
        while self._running:
            try:
                self.fleet.check_health()
                self.fleet._process_parked()
            except asyncio.CancelledError:
                raise
            except Exception:                         # noqa: BLE001
                self._on_loop_error("monitor")
            await asyncio.sleep(self.health_interval_s)

    # ---- client API ------------------------------------------------------
    async def submit(self, prompt_ids, **kw) -> TokenStream:
        """Admit one request (Fleet.submit semantics and typed sheds)
        and return its event stream."""
        return TokenStream(self.fleet.submit(prompt_ids, **kw))

    async def generate(self, prompt_ids,
                       **kw) -> Tuple[List[int], Optional[str]]:
        """Non-streaming convenience: submit and await completion."""
        stream = await self.submit(prompt_ids, **kw)
        return await stream.collect()

    async def abort(self, request_id: int) -> bool:
        return self.fleet.abort(request_id)

    async def drain(self, name: str) -> int:
        """Deliberately drain one replica; its stepping task exits on
        its own (the state flips out of HEALTHY) and in-flight work
        migrates with the zero-loss contract."""
        return self.fleet.drain(name)

    # ---- observability (ISSUE 10) ----------------------------------------
    def metrics_text(self, *, prefix: str = "paddle_serving") -> str:
        """The Prometheus scrape body for this server — the exposition
        hook a future HTTP transport mounts at /metrics (synchronous on
        purpose: it reads host-side counters only, no engine step). One
        call renders the merged fleet view plus per-replica labeled
        series via `Fleet.prometheus_text`."""
        return self.fleet.prometheus_text(prefix=prefix)
