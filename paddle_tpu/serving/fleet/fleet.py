"""Fleet core: multiplex requests over N in-process ServingEngine
replicas with prefix-affinity routing, SLO/tenant admission, replica
supervision, and ZERO-LOSS failover.

This is the synchronous heart of the fleet front-end (the asyncio
streaming API in server.py is a thin shell over it) — deliberately so:
the chaos soak and the failover acceptance tests drive `step_all()`
directly, with every engine, heartbeat, and deadline on one injectable
clock, so a replica kill is a deterministic, replayable event.

Request lifecycle:

    submit() --route--> replica engine --step emissions--> FleetHandle
       |                     |
       |  (crash/stall/drain)|  snapshot -> PARKED (catch-up tokens
       |                     v   delivered; deadline keeps ticking)
       |                _process_parked --adopt--> surviving replica
       +-- shed (TenantThrottled / SloUnattainable / EngineOverloaded)

Zero-loss contract (the chaos-soak acceptance criterion): when a
replica dies or drains mid-stream, every non-finished request re-lands
on a survivor with its tokens-so-far preserved — the stream sees each
token EXACTLY once (snapshot tokens the stream never saw are delivered
as catch-up at migration; the resumed engine re-prefills prompt+output
and only ever emits NEW tokens), and greedy output is bit-identical to
an uninterrupted run because every replica runs the same model under
the same bucket grid (the SERVING.md determinism contract). The dead
replica's pool reclaims fully (`ServingEngine.vacate`). Requests that
FINISHED inside the very step that killed the replica lost their
emissions with the raise — their tokens are recovered from
`request.output_ids` at evacuation, same exactly-once rule.

SLO-aware admission: `ttft_slo_s` / `tpot_slo_s` targets convert into
the engine's existing deadline machinery (deadline = TTFT budget +
TPOT * max_new_tokens) and, when the fleet has a TTFT estimator, into
an admission-time shed (`SloUnattainable`) — refusing work that would
only expire in the queue. Per-tenant fairness is an admission cap on
each tenant's live share of fleet capacity (`TenantThrottled`).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from ...utils import faults
from ..engine import check_snapshot_version
from ..errors import EngineFailure, EngineOverloaded
from ..lora.adapter import AdapterNotLoaded
from ..metrics import ServingMetrics
from ..scheduler import RequestState
from .errors import (NoHealthyReplica, ReplicaCrashed, SloUnattainable,
                     TenantThrottled)
from .replica import Replica, ReplicaState
from .router import PrefixAffinityRouter, Router

__all__ = ["Fleet", "FleetHandle", "FAULT_ROUTE_RACE"]

# Routing race (ISSUE 7 fault point, table in SERVING.md): fires after
# the router scored and chose — a payload means "the chosen replica
# went unhealthy between scoring and submission", so the fleet must
# re-route among the remaining candidates instead of submitting into a
# void. With one candidate left the firing is consumed but ignored
# (there is nobody else to race to).
FAULT_ROUTE_RACE = faults.register_point("fleet.route_race")

_DEFAULT_TENANT = "_default"


# single source of the streamed event shapes: live emission, a late
# stream's replay, and the synthetic close event must never drift apart
def token_event(handle: "FleetHandle", tok: int, index: int) -> dict:
    return {"type": "token", "token": int(tok), "index": int(index),
            "request_id": handle.request_id}


def finish_event(handle: "FleetHandle", reason) -> dict:
    return {"type": "finish", "finish_reason": reason,
            "num_tokens": len(handle.tokens),
            "request_id": handle.request_id}


class FleetHandle:
    """Client-side view of one fleet request: the stable request id
    (engine request ids are process-global, so the id survives
    migration), tokens delivered so far, and the terminal state. The
    async streaming layer `subscribe`s listeners to receive token /
    finish events as they happen (several streams may watch one
    handle); synchronous callers read `.tokens` after `Fleet.run()`."""

    __slots__ = ("request_id", "tenant", "tokens", "finished",
                 "finish_reason", "migrations", "_listeners",
                 "submit_t", "first_token_t", "finish_t",
                 "ttft_slo_s", "tpot_slo_s", "token_ts")

    def __init__(self, request_id: int, tenant: str):
        self.request_id = int(request_id)
        self.tenant = tenant
        self.tokens: List[int] = []
        self.finished = False
        self.finish_reason: Optional[str] = None
        self.migrations = 0
        self._listeners: List = []     # callables(event dict)
        # SLO-burn accounting (ISSUE 10): stamps on the FLEET clock +
        # the targets the request was admitted under; _finalize turns
        # observed-vs-target into the slo_*_violations counters
        self.submit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.ttft_slo_s: Optional[float] = None
        self.tpot_slo_s: Optional[float] = None
        # per-token delivery stamps on the fleet clock (ISSUE 18):
        # inter-token gaps after the first token are the decode TPOT
        # samples the disagg soak compares against co-location.
        # Catch-up bursts land many tokens on one stamp — TPOT readers
        # must use clean (migration-free) passes.
        self.token_ts: List[float] = []

    def subscribe(self, listener):
        """Attach an event callback; every attached listener sees every
        subsequent event (a second stream must not detach the first).
        Listeners are released at finish (no further events can ever
        fire), and subscribing to an already-finished handle is a no-op
        for the same reason — streams replay a finished handle from its
        state, so pinning a listener would only leak the caller's
        queue. Detach a live one early with `unsubscribe`."""
        if not self.finished:
            self._listeners.append(listener)

    def unsubscribe(self, listener):
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def _emit_event(self, event: dict):
        for cb in self._listeners:
            cb(event)

    # exactly-once delivery funnel: every token a client ever sees —
    # live emission or migration catch-up — passes through here once
    def _deliver(self, tok: int):
        self.tokens.append(int(tok))
        self._emit_event(token_event(self, tok, len(self.tokens) - 1))

    def _finish(self, reason: str):
        if self.finished:
            return
        self.finished = True
        self.finish_reason = reason
        self._emit_event(finish_event(self, reason))
        # terminal: nothing will ever be emitted again, so drop the
        # listeners (each holds a stream queue) — late-attached streams
        # replay from the handle's state, not from events
        self._listeners = []

    def __repr__(self):
        state = self.finish_reason if self.finished else "live"
        return (f"FleetHandle({self.request_id}, {state}, "
                f"tokens={len(self.tokens)})")


class Fleet:
    """N supervised replicas behind one submit/step façade.

    engines: the in-process ServingEngine replicas (normally sharing
    one model object — engines snapshot the weights read-only — and,
    for deadline-correct migration, the SAME `clock` passed here: a
    parked request's deadline keeps ticking on the fleet clock and is
    re-anchored on the target engine's clock at adoption, which only
    lines up when they agree).

    Supervision knobs: `stall_timeout_s` (heartbeat age that marks a
    working replica unhealthy), `max_consecutive_failures` (step
    exceptions in a row before eviction from rotation). Admission
    knobs: `max_inflight_per_tenant` (per-tenant fairness cap on live
    requests), `est_ttft_per_queued_s` (optional per-queued-request
    TTFT estimate powering the SLO admission shed).
    """

    def __init__(self, engines, *, router: Optional[Router] = None,
                 clock=None, stall_timeout_s: float = 5.0,
                 max_consecutive_failures: int = 3,
                 max_inflight_per_tenant: Optional[int] = None,
                 est_ttft_per_queued_s: Optional[float] = None,
                 max_retained_handles: int = 4096,
                 names: Optional[List[str]] = None):
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        self._clock = clock if clock is not None else time.monotonic
        if names is None:
            names = [f"replica-{i}" for i in range(len(engines))]
        if len(names) != len(engines) or len(set(names)) != len(names):
            raise ValueError("names must be unique, one per engine")
        self.replicas = [Replica(n, e, clock=self._clock)
                         for n, e in zip(names, engines)]
        self.router = router if router is not None \
            else PrefixAffinityRouter()
        self.stall_timeout_s = float(stall_timeout_s)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.max_inflight_per_tenant = max_inflight_per_tenant
        self.est_ttft_per_queued_s = est_ttft_per_queued_s

        self._handles: Dict[int, FleetHandle] = {}
        # bounded finished-handle retention (same unbounded-growth class
        # the engine bounds with max_retained_finished): a long-lived
        # server must not keep every handle it ever served — only the
        # most recent `max_retained_handles` finished ones stay readable
        # via fleet.handle(); callers' own references live on untouched
        self.max_retained_handles = int(max_retained_handles)
        self._finished_order: deque = deque()
        self.num_evicted_handles = 0
        self._assign: Dict[int, Replica] = {}
        self._by_replica: Dict[str, Set[int]] = {r.name: set()
                                                 for r in self.replicas}
        # (snapshot_time, request record) parked between a replica's
        # death/drain and re-landing on a survivor
        self._parked: List[Tuple[float, dict]] = []
        self._tenant_live: Dict[str, int] = {}
        self.counters: Dict[str, int] = {
            "requests_submitted": 0,
            "requests_finished": 0,
            "requests_migrated": 0,
            "requests_lost": 0,
            "requests_shed": 0,
            "catchup_tokens": 0,
            "replica_deaths": 0,
            "replica_stalls": 0,
            "replica_drains": 0,
            "route_races": 0,
            "tenant_throttled": 0,
            "slo_sheds": 0,
            # SLO burn (ISSUE 10): requests whose OBSERVED TTFT/TPOT
            # missed the target they were admitted under — the
            # admission shed above refuses hopeless work, these count
            # accepted work that still burned its budget
            "slo_ttft_violations": 0,
            "slo_tpot_violations": 0,
            # ISSUE 15: parked adapter'd requests that could not re-land
            # because NO survivor held their adapter — kept parked
            # (typed), re-tried each parked sweep, never served with
            # the wrong weights and never silently lost
            "adapter_parks": 0,
        }

    # ---- lookups ---------------------------------------------------------
    def replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"unknown replica {name!r}")

    def _healthy(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state is ReplicaState.HEALTHY]

    def handle(self, request_id: int) -> FleetHandle:
        """Look up a tracked handle. Finished handles older than the
        retention window are forgotten (KeyError) — callers that need a
        result past that should keep the handle submit() returned."""
        return self._handles[request_id]

    def has_work(self) -> bool:
        return bool(self._parked or self._assign)

    # ---- admission -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               eos_token_id: Optional[int] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None,
               ttl_s: Optional[float] = None,
               deadline: Optional[float] = None,
               ttft_slo_s: Optional[float] = None,
               tpot_slo_s: Optional[float] = None) -> FleetHandle:
        """Route and queue one request; returns its FleetHandle.

        SLO targets convert into the deadline machinery: the request
        must produce its first token within `ttft_slo_s` and then
        sustain `tpot_slo_s` per token, so its whole lifetime is
        bounded by ttft + tpot * max_new_tokens — passed down as the
        engine TTL when `tpot_slo_s` is given (mutually exclusive with
        an explicit ttl_s / deadline; a ttft-only target drives the
        admission-time shed but sets no TTL — the deadline bounds the
        whole lifetime, which only the per-token rate can size). Sheds are typed: `TenantThrottled` (fairness cap),
        `SloUnattainable` (TTFT target hopeless at current load),
        `EngineOverloaded` (every candidate's queue full),
        `NoHealthyReplica` (nobody in rotation), `AdapterNotLoaded`
        (ISSUE 15: no candidate replica holds the named adapter —
        routing prefers adapter-holding replicas, and an adapter'd
        request sheds typed rather than ever serving other weights;
        per-adapter fairness rides the existing `tenant` cap — pass
        the adapter (or its owner) as the tenant to cap its live
        share)."""
        self._process_parked()
        tkey = tenant if tenant is not None else _DEFAULT_TENANT
        if self.max_inflight_per_tenant is not None and \
                self._tenant_live.get(tkey, 0) >= \
                self.max_inflight_per_tenant:
            self.counters["tenant_throttled"] += 1
            raise TenantThrottled(
                f"tenant {tkey!r} already holds "
                f"{self._tenant_live.get(tkey, 0)} live requests "
                f"(cap {self.max_inflight_per_tenant})",
                tenant=tkey, live=self._tenant_live.get(tkey, 0),
                limit=self.max_inflight_per_tenant)
        if ttft_slo_s is not None or tpot_slo_s is not None:
            if ttl_s is not None or deadline is not None:
                raise ValueError("pass SLO targets or ttl_s/deadline, "
                                 "not both")
            if tpot_slo_s is not None:
                ttl_s = ((ttft_slo_s or 0.0)
                         + tpot_slo_s * int(max_new_tokens))
            # ttft-only: the deadline machinery bounds a request's
            # WHOLE lifetime, so using the TTFT budget as the TTL would
            # expire a request mid-generation even after its first
            # token met the target — without a per-token rate there is
            # no honest lifetime bound, so a ttft-only target drives
            # the admission shed below and nothing else
        candidates = self._healthy()
        if not candidates:
            raise NoHealthyReplica("no healthy replica to accept work")
        prompt_ids = [int(t) for t in prompt_ids]
        est_floor = None
        overloaded_holder = None
        while True:
            chosen = self.router.route(prompt_ids, candidates,
                                       adapter=adapter)
            if ttft_slo_s is not None and self.est_ttft_per_queued_s:
                # the SLO check scores the replica the request would
                # ACTUALLY land on — scoring the fleet minimum would
                # admit a request the router then routes into a deep
                # queue, accepted only to expire. A too-deep choice is
                # excluded and the rest retried; only when every
                # candidate fails does the shed surface.
                est = (chosen.engine.scheduler.queue_depth
                       * self.est_ttft_per_queued_s)
                if est > ttft_slo_s:
                    est_floor = est if est_floor is None \
                        else min(est_floor, est)
                    candidates = [c for c in candidates
                                  if c is not chosen]
                    if candidates:
                        continue
                    self.counters["slo_sheds"] += 1
                    raise SloUnattainable(
                        f"estimated TTFT {est_floor:.3f}s exceeds the "
                        f"{ttft_slo_s:.3f}s target on every replica",
                        ttft_slo_s=ttft_slo_s, est_ttft_s=est_floor)
            if faults.fire(FAULT_ROUTE_RACE) is not None and \
                    len(candidates) > 1:
                # chosen went unhealthy between scoring and submission:
                # retry among the others
                self.counters["route_races"] += 1
                candidates = [c for c in candidates if c is not chosen]
                continue
            try:
                rid = chosen.engine.add_request(
                    prompt_ids, max_new_tokens=max_new_tokens,
                    eos_token_id=eos_token_id, ttl_s=ttl_s,
                    deadline=deadline, adapter=adapter)
            except (EngineOverloaded, AdapterNotLoaded) as exc:
                # typed per-candidate refusal (queue full, or the
                # chosen replica does not hold the adapter): try the
                # rest. When everyone refuses, surface the MOST
                # ACTIONABLE shed: an overload from a replica that DOES
                # hold the adapter outranks "adapter not loaded"
                # elsewhere — a retryable 429, not a spurious 404
                # claiming the adapter is missing from the fleet.
                if isinstance(exc, EngineOverloaded):
                    overloaded_holder = exc
                candidates = [c for c in candidates if c is not chosen]
                if not candidates:
                    self.counters["requests_shed"] += 1
                    if isinstance(exc, AdapterNotLoaded) and \
                            overloaded_holder is not None:
                        raise overloaded_holder from exc
                    raise
                continue
            break
        handle = FleetHandle(rid, tkey)
        handle.submit_t = self._clock()
        handle.ttft_slo_s = ttft_slo_s
        handle.tpot_slo_s = tpot_slo_s
        tracer = getattr(chosen.engine, "tracer", None)
        if tracer is not None:
            # the routing decision, with the scores it was made on —
            # the read-only match_len probe re-runs only when tracing
            tracer.mark(rid, "route", chosen=chosen.name,
                        scores={c.name: {"match_len":
                                         c.match_len(prompt_ids,
                                                     adapter=adapter),
                                         "load": c.load}
                                for c in candidates})
        self._handles[rid] = handle
        self._assign_to(rid, chosen)
        self._tenant_live[tkey] = self._tenant_live.get(tkey, 0) + 1
        self.counters["requests_submitted"] += 1
        return handle

    def abort(self, request_id: int) -> bool:
        """Client abort, wherever the request currently lives: on its
        replica (engine abort, honored at the next boundary), or PARKED
        mid-migration (the flag rides the snapshot record and the
        target engine honors it at its first boundary — the pages the
        dead replica held were already freed exactly once at
        evacuation, and the target frees its own exactly once at
        cancel). Returns False for unknown/finished requests."""
        replica = self._assign.get(request_id)
        if replica is not None:
            return replica.engine.abort(request_id)
        for _, rec in self._parked:
            if rec["request_id"] == request_id:
                rec["aborted"] = True
                return True
        return False

    # ---- assignment bookkeeping -----------------------------------------
    def _assign_to(self, rid: int, replica: Replica):
        self._assign[rid] = replica
        self._by_replica[replica.name].add(rid)

    def _unassign(self, rid: int):
        replica = self._assign.pop(rid, None)
        if replica is not None:
            self._by_replica[replica.name].discard(rid)

    def _finalize(self, rid: int, reason: str):
        self._unassign(rid)
        handle = self._handles.get(rid)
        if handle is None or handle.finished:
            return
        handle.finish_t = self._clock()
        self._account_slo(handle)
        handle._finish(reason)
        self._tenant_live[handle.tenant] = max(
            0, self._tenant_live.get(handle.tenant, 1) - 1)
        if reason == "lost":
            self.counters["requests_lost"] += 1
            tracer = self._tracer()
            if tracer is not None:
                # every other terminal reason finishes its trace on the
                # owning engine; "lost" has no engine left to do it
                tracer.finish(rid, "lost")
        else:
            self.counters["requests_finished"] += 1
        self._finished_order.append(rid)
        while len(self._finished_order) > self.max_retained_handles:
            self._handles.pop(self._finished_order.popleft(), None)
            self.num_evicted_handles += 1

    def _tracer(self):
        """The (shared) request tracer, when any replica's engine has
        one. A fleet that traces passes ONE RequestTracer to every
        engine — the first found is the fleet's."""
        for r in self.replicas:
            t = getattr(r.engine, "tracer", None)
            if t is not None:
                return t
        return None

    def _deliver(self, handle: FleetHandle, tok: int):
        """Exactly-once delivery + the first-token SLO stamp (catch-up
        and live emission both land here, so TTFT is observed whichever
        path a migrated request's first token took)."""
        handle._deliver(tok)
        if handle.first_token_t is None:
            handle.first_token_t = self._clock()

    def _account_slo(self, handle: FleetHandle):
        """Observed-vs-target SLO burn at finalize (ISSUE 10): a TTFT
        target is violated when the first token came late (or never); a
        TPOT target when the per-token rate after the first token ran
        slower than admitted. Counted once per request, on the same
        fleet clock the deadline machinery runs on."""
        if handle.ttft_slo_s is not None and handle.submit_t is not None:
            if handle.first_token_t is None or \
                    handle.first_token_t - handle.submit_t \
                    > handle.ttft_slo_s:
                self.counters["slo_ttft_violations"] += 1
        if handle.tpot_slo_s is not None and \
                handle.first_token_t is not None and \
                len(handle.tokens) > 1 and handle.finish_t is not None:
            tpot = (handle.finish_t - handle.first_token_t) \
                / (len(handle.tokens) - 1)
            if tpot > handle.tpot_slo_s:
                self.counters["slo_tpot_violations"] += 1

    def _catch_up(self, handle: FleetHandle, output_ids):
        """Deliver the suffix of `output_ids` the stream has not seen.
        Tokens delivered live are a prefix of the engine's output_ids
        by construction (emission appends in the same order), so the
        suffix rule is exactly-once delivery."""
        for tok in output_ids[len(handle.tokens):]:
            self._deliver(handle, tok)
            self.counters["catchup_tokens"] += 1

    # ---- stepping + supervision -----------------------------------------
    def step_replica(self, replica: Replica) -> List[Tuple[int, int]]:
        """One supervised step of one replica: re-land any parked work
        first (any replica's loop may pick it up), step the engine,
        deliver emissions to handles, sweep finished requests, and
        apply the supervision policy to anything `step()` raised."""
        self._process_parked()
        if replica.state is not ReplicaState.HEALTHY:
            return []
        try:
            emitted = replica.step()
        except ReplicaCrashed:
            self._fail_replica(replica, ReplicaState.DEAD,
                               replica.engine.snapshot(
                                   reason=f"crash of {replica.name}"))
            return []
        except Exception as exc:                      # noqa: BLE001
            if isinstance(exc, EngineFailure):
                snap = exc.snapshot if exc.snapshot is not None \
                    else replica.engine.last_snapshot
                self._fail_replica(replica, ReplicaState.DEAD, snap)
                return []
            replica.consecutive_failures += 1
            if replica.consecutive_failures >= \
                    self.max_consecutive_failures:
                self._fail_replica(
                    replica, ReplicaState.UNHEALTHY,
                    replica.engine.snapshot(
                        reason=f"{replica.consecutive_failures} "
                               f"consecutive step failures on "
                               f"{replica.name}"))
            return []
        for rid, tok in emitted:
            handle = self._handles.get(rid)
            if handle is not None:
                self._deliver(handle, tok)
        self._sweep_finished(replica)
        return emitted

    def step_all(self) -> int:
        """One fleet iteration: step every healthy replica once, then
        run health checks (stall detection). Returns tokens emitted."""
        iter_start = self._clock()
        n = 0
        for replica in self.replicas:
            n += len(self.step_replica(replica))
        self.check_health(iter_start=iter_start)
        return n

    def check_health(self, iter_start: Optional[float] = None):
        """Stall detection: a HEALTHY replica with work whose heartbeat
        is older than `stall_timeout_s` is marked UNHEALTHY and
        evacuated — from the outside a wedged stepping loop and a dead
        one are the same thing: no progress.

        Saturation guard: with more than one replica, eviction also
        requires some OTHER healthy replica to have progressed
        meaningfully past the suspect's heartbeat — when EVERY
        heartbeat is equally old the stepping loop itself is merely
        slow/saturated (synchronous engine steps sharing one event
        loop), and evicting healthy replicas one by one would cascade
        to finalizing all in-flight work "lost" with no real fault.
        Single-replica fleets fall back to the raw timeout (there is
        nobody to compare against).

        `iter_start` (step_all passes its loop-entry time): a replica
        whose heartbeat is AT or PAST it completed a successful step
        THIS iteration and is exempt — the replicas step sequentially,
        so one slow sibling step (a cold first-step compile takes >5 s
        on a cold XLA cache) would otherwise age an earlier, perfectly
        live replica straight past the timeout. Genuinely wedged
        replicas never stamp `last_progress` (the fault-stall path
        skips the engine step without touching the heartbeat), so
        detection is unchanged."""
        now = self._clock()
        for r in list(self.replicas):
            if r.state is not ReplicaState.HEALTHY or \
                    not r.engine.has_work():
                continue
            if iter_start is not None and r.last_progress >= iter_start:
                continue
            if now - r.last_progress <= self.stall_timeout_s:
                continue
            others = [o for o in self.replicas
                      if o is not r and o.state is ReplicaState.HEALTHY]
            if others and not any(
                    o.last_progress - r.last_progress
                    > self.stall_timeout_s for o in others):
                continue
            self.counters["replica_stalls"] += 1
            self._fail_replica(
                r, ReplicaState.UNHEALTHY,
                r.engine.snapshot(reason=f"stall on {r.name}"))

    def _sweep_finished(self, replica: Replica):
        """Finalize handles whose requests reached a terminal state on
        this replica (finish reasons surface verbatim: "stop",
        "length", "abort", "expired", "quarantined")."""
        for rid in list(self._by_replica.get(replica.name, ())):
            req = replica.engine.requests.get(rid)
            if req is None:
                # evicted from the bounded retention window before the
                # fleet observed a terminal state (cannot happen at the
                # default window; belt-and-braces)
                self._finalize(rid, "lost")
            elif req.state is RequestState.FINISHED:
                self._finalize(rid, req.finish_reason)

    # ---- failover --------------------------------------------------------
    def _fail_replica(self, replica: Replica, state: ReplicaState,
                      snapshot: dict):
        """Take `replica` out of rotation and turn its snapshot into
        parked migration work; then reclaim its entire pool."""
        replica.state = state
        if state is ReplicaState.DEAD:
            self.counters["replica_deaths"] += 1
        self._evacuate(replica, snapshot)

    def _evacuate(self, replica: Replica, snapshot: dict):
        """The zero-loss handoff: park every snapshot-captured request
        for re-landing; recover the tokens of requests that FINISHED
        inside the fatal step (their emissions died with the raise);
        then free every page the replica held (`vacate` — the
        reclamation the soak asserts)."""
        check_snapshot_version(snapshot)
        recs = {rec["request_id"]: rec for rec in snapshot["requests"]}
        now = self._clock()
        tracer = getattr(replica.engine, "tracer", None)
        for rid in list(self._by_replica.get(replica.name, ())):
            rec = recs.get(rid)
            if rec is not None:
                self._unassign(rid)
                self._parked.append((now, rec))
                if tracer is not None:
                    # migration PARK: the trace stays live (the work
                    # re-lands; `adopt` marks the landing)
                    tracer.mark(rid, "park", replica=replica.name,
                                reason=str(snapshot.get("reason")))
                continue
            req = replica.engine.requests.get(rid)
            if req is not None and req.state is RequestState.FINISHED \
                    and req.finish_reason != "migrated":
                handle = self._handles.get(rid)
                if handle is not None:
                    self._catch_up(handle, req.output_ids)
                self._finalize(rid, req.finish_reason)
            else:
                self._finalize(rid, "lost")
        replica.engine.vacate()

    def _process_parked(self) -> int:
        """Re-land parked requests on survivors: catch-up tokens to the
        stream, deadline re-anchored with the PARKED time charged
        against it (a request whose deadline lapsed while parked is
        adopted and expires at the target's first boundary — before it
        allocates any pages there), prefix-affinity routed on its full
        resume prompt. With zero survivors the requests are finalized
        "lost" — zero-loss needs somewhere to land."""
        if not self._parked:
            return 0
        healthy = self._healthy()
        parked, self._parked = self._parked, []
        landed = 0
        for t0, rec in parked:
            rid = rec["request_id"]
            handle = self._handles.get(rid)
            if handle is None or handle.finished:
                continue
            if not healthy:
                self._finalize(rid, "lost")
                continue
            self._catch_up(handle, rec["output_ids"])
            rec = dict(rec)
            rem = rec.get("deadline_remaining_s")
            if rem is not None:
                rec["deadline_remaining_s"] = \
                    float(rem) - (self._clock() - t0)
            # adoption must not drop the REST of the parked list on one
            # bad record: a survivor can legitimately refuse a request
            # its geometry cannot hold (heterogeneous pools /
            # max_seq_len). Try every healthy candidate; only when all
            # refuse is the request finalized "lost" — never silently
            # vanished, never an exception up through an unrelated
            # caller's submit()/step loop. Exception (ISSUE 15): an
            # adapter'd record every survivor refused FOR THE ADAPTER
            # stays PARKED (typed, counted) — it re-lands the moment
            # some replica loads the adapter, and is never served with
            # the wrong weights nor finalized lost while survivors
            # exist.
            candidates = list(healthy)
            target = None
            adapter_refusals = other_refusals = 0
            while candidates:
                pick = self.router.route(
                    rec["prompt_ids"] + rec["output_ids"], candidates,
                    adapter=rec.get("adapter"))
                try:
                    pick.engine.adopt_requests([rec])
                except AdapterNotLoaded:
                    adapter_refusals += 1
                    candidates = [c for c in candidates if c is not pick]
                    continue
                except Exception:                     # noqa: BLE001
                    other_refusals += 1
                    candidates = [c for c in candidates if c is not pick]
                    continue
                target = pick
                break
            if target is None:
                if adapter_refusals and not other_refusals:
                    rem = rec.get("deadline_remaining_s")
                    if rem is not None and rem <= 0:
                        # its TTL lapsed while waiting for the adapter:
                        # expire (the terminal an adopter would apply)
                        # instead of parking a dead request forever
                        self._finalize(rid, "expired")
                    else:
                        self.counters["adapter_parks"] += 1
                        self._parked.append((self._clock(), rec))
                else:
                    self._finalize(rid, "lost")
                continue
            self._assign_to(rid, target)
            handle.migrations += 1
            self.counters["requests_migrated"] += 1
            landed += 1
        return landed

    # ---- drain (deliberate) ---------------------------------------------
    def drain(self, name: str) -> int:
        """Deliberately empty one replica: out of rotation, snapshot
        becomes live migration exactly like a crash (same parked path,
        same exactly-once token rule), pool fully reclaimed. Returns
        the number of requests handed off."""
        replica = self.replica(name)
        if replica.state is not ReplicaState.HEALTHY:
            return 0
        replica.state = ReplicaState.DRAINED
        self.counters["replica_drains"] += 1
        before = len(self._by_replica.get(replica.name, ()))
        self._evacuate(replica, replica.engine.snapshot(
            reason=f"drain of {replica.name}"))
        self._process_parked()
        return before

    # ---- convenience / lifecycle ----------------------------------------
    def run(self, max_steps: Optional[int] = None) -> Dict[int, List[int]]:
        """Drain everything synchronously; {request_id: tokens} for
        every handle the fleet tracked at the call (references are
        pinned first, so the bounded retention window evicting a
        finished handle mid-drain cannot drop its results)."""
        tracked = dict(self._handles)
        if max_steps is None:
            max_steps = 1000 * max(1, len(tracked))
        steps = 0
        while self.has_work():
            self.step_all()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet failed to drain after {steps} steps")
        return {rid: list(h.tokens) for rid, h in tracked.items()}

    def merged_metrics(self) -> ServingMetrics:
        """One cross-replica ServingMetrics (unregistered view)."""
        return ServingMetrics.merge(
            *[r.engine.metrics for r in self.replicas], name="fleet")

    def summary(self) -> dict:
        """Merged engine metrics + fleet counters + replica health."""
        snap = self.merged_metrics().snapshot()
        snap.update({f"fleet_{k}": v for k, v in self.counters.items()})
        snap["replica_states"] = {r.name: r.state.value
                                  for r in self.replicas}
        return snap

    def prometheus_text(self, *, prefix: str = "paddle_serving") -> str:
        """The fleet as one Prometheus scrape (ISSUE 10): the merged
        engine metrics and fleet counters (from `summary()` — the
        exposition derives from the same snapshot path, so they can
        never disagree), then every replica's OWN engine metrics under
        a `replica="<name>"` label (per-replica visibility is the point
        of the labels; Prometheus aggregates in queries). TYPE lines
        are emitted once, on the merged block."""
        from ..exposition import (metric_name, prometheus_lines,
                                  sanitize_label_value)
        merged = self.merged_metrics()
        counter_keys = set(merged.counters) | {
            f"fleet_{k}" for k in self.counters}
        lines = prometheus_lines(self.summary(),
                                 counter_keys=counter_keys,
                                 prefix=prefix)
        for r in self.replicas:
            lines.append(f'{metric_name(prefix, "replica_up")}'
                         f'{{replica="{sanitize_label_value(r.name)}"}} '
                         f'{int(r.state is ReplicaState.HEALTHY)}')
            lines.extend(prometheus_lines(
                r.engine.metrics.snapshot(),
                counter_keys=set(r.engine.metrics.counters),
                prefix=prefix, labels={"replica": r.name},
                emit_type=False))
        return "\n".join(lines) + "\n"

    def shutdown(self):
        for r in self.replicas:
            r.engine.shutdown()
