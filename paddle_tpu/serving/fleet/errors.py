"""Typed failure surface of the fleet layer.

Engine-level errors live in `serving.errors`; these are failures of the
layer ABOVE it — routing, replica supervision, and fleet admission:

* `NoHealthyReplica` — the router has no candidate: every replica is
  dead, drained, or unhealthy. Submission-time only; requests already
  accepted are migrated (or, with zero survivors, finalized "lost").
* `TenantThrottled` — per-tenant fairness cap hit: this tenant already
  holds its share of fleet capacity. Subclasses `EngineOverloaded` so
  callers that treat sheds uniformly (retry-after, backpressure) keep
  working without a new except arm.
* `SloUnattainable` — SLO-aware admission refused the request: even the
  least-loaded replica cannot plausibly meet the requested TTFT target.
  Shedding at the door beats accepting work that will expire mid-queue
  (the deadline machinery would cancel it anyway, after it wasted pages
  and budget). Also an `EngineOverloaded` subclass.
* `ReplicaCrashed` — the hard-crash signal the `fleet.replica_crash`
  fault point raises inside a replica's stepping loop; the fleet treats
  it as the replica process dying at an iteration boundary.
"""
from __future__ import annotations

from ..errors import EngineOverloaded

__all__ = ["NoHealthyReplica", "TenantThrottled", "SloUnattainable",
           "ReplicaCrashed"]


class NoHealthyReplica(RuntimeError):
    """Every replica is out of rotation; nothing can accept work."""


class TenantThrottled(EngineOverloaded):
    """Per-tenant fairness cap: the tenant's live-request share of the
    fleet is already at its limit."""

    def __init__(self, msg: str, tenant=None, live: int = 0,
                 limit: int = 0):
        super().__init__(msg, queue_depth=live, max_queue_len=limit)
        self.tenant = tenant
        self.live = live
        self.limit = limit


class SloUnattainable(EngineOverloaded):
    """Admission-time SLO check failed: the TTFT target cannot be met
    at current load, so the request is shed instead of accepted-to-
    expire."""

    def __init__(self, msg: str, ttft_slo_s=None, est_ttft_s=None):
        super().__init__(msg)
        self.ttft_slo_s = ttft_slo_s
        self.est_ttft_s = est_ttft_s


class ReplicaCrashed(RuntimeError):
    """Injected hard crash of one replica (fault point
    `fleet.replica_crash` with a payload naming the victim)."""
