"""paddle_tpu.serving.fleet — multi-replica serving front-end (ISSUE 7).

The layer above the engine: N in-process ServingEngine replicas behind
one streaming API, with prefix-affinity routing (the PR-2 radix hit
rate as a fleet property), SLO/tenant-aware admission riding the PR-3
deadline + shed machinery, replica supervision (heartbeats, stall and
consecutive-failure detection), and ZERO-LOSS failover — the PR-3
snapshot turned into live migration, with tokens-so-far preserved and
greedy output bit-identical to an uninterrupted run (SERVING.md
"Fleet front-end").

Sync core: `Fleet` (submit/step_all/run — what the chaos soak drives
deterministically). Async shell: `FleetServer` (per-replica stepping
tasks + `TokenStream` async iterators).
"""
from .errors import (NoHealthyReplica, ReplicaCrashed, SloUnattainable,
                     TenantThrottled)
from .fleet import Fleet, FleetHandle
from .replica import Replica, ReplicaState
from .router import (PrefixAffinityRouter, RandomRouter, RoundRobinRouter,
                     Router)
from .server import FleetServer, TokenStream

__all__ = ["Fleet", "FleetHandle", "FleetServer", "TokenStream",
           "Replica", "ReplicaState", "Router", "PrefixAffinityRouter",
           "RandomRouter", "RoundRobinRouter", "NoHealthyReplica",
           "TenantThrottled", "SloUnattainable", "ReplicaCrashed"]
