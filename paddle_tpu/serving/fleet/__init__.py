"""paddle_tpu.serving.fleet — multi-replica serving front-end (ISSUE 7).

The layer above the engine: N in-process ServingEngine replicas behind
one streaming API, with prefix-affinity routing (the PR-2 radix hit
rate as a fleet property), SLO/tenant-aware admission riding the PR-3
deadline + shed machinery, replica supervision (heartbeats, stall and
consecutive-failure detection), and ZERO-LOSS failover — the PR-3
snapshot turned into live migration, with tokens-so-far preserved and
greedy output bit-identical to an uninterrupted run (SERVING.md
"Fleet front-end").

Sync core: `Fleet` (submit/step_all/run — what the chaos soak drives
deterministically). Async shell: `FleetServer` (per-replica stepping
tasks + `TokenStream` async iterators), fronted over the wire by
`HttpFrontend` (HTTP/SSE, ISSUE 14).

Cross-process tier (ISSUE 14): `ProcessFleet` supervises replica
WORKER PROCESSES (worker.py) over the framed TCPStore mailbox
(transport.py) — process-isolated failure domains, crash-proof
restart via heartbeat-shipped snapshots, and rolling restarts that
skip the compile storm through the persistent
`serving.compile_cache.CompileCache`.
"""
from .errors import (NoHealthyReplica, ReplicaCrashed, SloUnattainable,
                     TenantThrottled)
from .fleet import Fleet, FleetHandle
from .http import HttpFrontend
from .procfleet import ProcessFleet, WorkerProc, WorkerState
from .replica import Replica, ReplicaState
from .router import (PrefixAffinityRouter, RandomRouter, RoundRobinRouter,
                     Router)
from .server import FleetServer, TokenStream
from .transport import Channel, TransportError

__all__ = ["Fleet", "FleetHandle", "FleetServer", "TokenStream",
           "Replica", "ReplicaState", "Router", "PrefixAffinityRouter",
           "RandomRouter", "RoundRobinRouter", "NoHealthyReplica",
           "TenantThrottled", "SloUnattainable", "ReplicaCrashed",
           "HttpFrontend", "ProcessFleet", "WorkerProc", "WorkerState",
           "Channel", "TransportError"]
