"""Request routing across replicas.

The production policy is PREFIX AFFINITY (`PrefixAffinityRouter`):
score every healthy replica by the longest prefix of the request its
radix cache already holds (the read-only `match_len` probe — scoring
must not perturb any replica's LRU order), and break ties by load
(in-flight + queue depth), then by name for determinism. This makes the
PR-2 radix hit rate a FLEET property: requests sharing a prompt prefix
keep landing on the replica that already holds its KV, instead of
re-prefetching the same prefix into every replica's cache (which is
what random spraying does — the soak's routing criterion measures
exactly that gap).

`RandomRouter` (seeded) and `RoundRobinRouter` exist as baselines for
that comparison and for workloads with no shared prefixes.

Routers are pure functions of (tokens, candidate list) plus their own
private state; the FLEET owns candidacy (health states, the route-race
retry) — a router never sees a dead replica.
"""
from __future__ import annotations

import random
from typing import List

from .errors import NoHealthyReplica
from .replica import Replica

__all__ = ["Router", "PrefixAffinityRouter", "RandomRouter",
           "RoundRobinRouter"]


class Router:
    """Strategy interface: pick one replica from the candidates."""

    def route(self, tokens, replicas: List[Replica]) -> Replica:
        raise NotImplementedError

    @staticmethod
    def _require(replicas: List[Replica]):
        if not replicas:
            raise NoHealthyReplica("no healthy replica to route to")


class PrefixAffinityRouter(Router):
    """Longest cached prefix first; least load, then name, break ties.

    With cold caches every score is 0, so the policy degrades to pure
    least-loaded — affinity only concentrates traffic once there is an
    actual prefix to be affine TO."""

    def route(self, tokens, replicas: List[Replica]) -> Replica:
        self._require(replicas)
        tokens = list(tokens)
        return min(replicas,
                   key=lambda r: (-r.match_len(tokens), r.load, r.name))


class RandomRouter(Router):
    """Seeded uniform spray — the routing-criterion baseline."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def route(self, tokens, replicas: List[Replica]) -> Replica:
        self._require(replicas)
        return replicas[self._rng.randrange(len(replicas))]


class RoundRobinRouter(Router):
    """Strict rotation over whoever is currently healthy."""

    def __init__(self):
        self._i = 0

    def route(self, tokens, replicas: List[Replica]) -> Replica:
        self._require(replicas)
        r = replicas[self._i % len(replicas)]
        self._i += 1
        return r
