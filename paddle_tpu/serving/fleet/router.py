"""Request routing across replicas.

The production policy is PREFIX AFFINITY (`PrefixAffinityRouter`):
score every healthy replica by the longest prefix of the request its
radix cache already holds (the read-only `match_len` probe — scoring
must not perturb any replica's LRU order), and break ties by load
(in-flight + queue depth), then by name for determinism. This makes the
PR-2 radix hit rate a FLEET property: requests sharing a prompt prefix
keep landing on the replica that already holds its KV, instead of
re-prefetching the same prefix into every replica's cache (which is
what random spraying does — the soak's routing criterion measures
exactly that gap).

`RandomRouter` (seeded) and `RoundRobinRouter` exist as baselines for
that comparison and for workloads with no shared prefixes.

Routers are pure functions of (tokens, candidate list) plus their own
private state; the FLEET owns candidacy (health states, the route-race
retry) — a router never sees a dead replica.
"""
from __future__ import annotations

import random
from typing import List

from .errors import NoHealthyReplica
from .replica import Replica

__all__ = ["Router", "PrefixAffinityRouter", "RandomRouter",
           "RoundRobinRouter", "role_candidates"]

# Which worker roles may serve each phase of a request's life
# (ISSUE 18 disaggregation). "both" workers serve either phase; a
# co-located fleet (all roles "both") matches every filter, so the
# helper is a no-op there.
_PHASE_ROLES = {
    "prefill": ("prefill", "both"),
    "decode": ("decode", "both"),
}


def role_candidates(candidates, phase: str):
    """Filter `candidates` (anything with a `.role` attribute) down to
    the ones whose role may serve `phase` ("prefill" or "decode").

    Role-aware routing FALLS BACK rather than sheds: when no candidate
    matches the phase (role-starved fleet — e.g. every decode worker is
    dead), the full candidate list is returned and the caller degrades
    to co-located execution on whatever is healthy."""
    want = _PHASE_ROLES[phase]
    matched = [c for c in candidates
               if getattr(c, "role", "both") in want]
    return matched or list(candidates)


class Router:
    """Strategy interface: pick one replica from the candidates.
    `adapter` (ISSUE 15) is the request's LoRA adapter name (None for
    base-model traffic) — policies may use it for placement; the
    baselines ignore it."""

    def route(self, tokens, replicas: List[Replica],
              adapter=None) -> Replica:
        raise NotImplementedError

    @staticmethod
    def _require(replicas: List[Replica]):
        if not replicas:
            raise NoHealthyReplica("no healthy replica to route to")


class PrefixAffinityRouter(Router):
    """Loaded-adapter match first, longest cached prefix second; least
    load, then name, break ties.

    The adapter score dominates (ISSUE 15): landing an adapter'd
    request on a replica that already HOLDS the adapter avoids a
    load/evict churn (or a typed refusal) entirely, and prefix
    affinity is worthless across adapters anyway — the radix key is
    adapter-namespaced, so only same-adapter replicas can have a
    matching prefix to begin with. The prefix probe uses the same
    namespaced key the scheduler matches with (read-only, no LRU
    perturbation). With cold caches and base-model traffic every score
    is 0 and the policy degrades to pure least-loaded."""

    def route(self, tokens, replicas: List[Replica],
              adapter=None) -> Replica:
        self._require(replicas)
        tokens = list(tokens)
        return min(replicas,
                   key=lambda r: (-int(r.has_adapter(adapter)),
                                  -r.match_len(tokens, adapter=adapter),
                                  r.load, r.name))


class RandomRouter(Router):
    """Seeded uniform spray — the routing-criterion baseline."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def route(self, tokens, replicas: List[Replica],
              adapter=None) -> Replica:
        self._require(replicas)
        return replicas[self._rng.randrange(len(replicas))]


class RoundRobinRouter(Router):
    """Strict rotation over whoever is currently healthy."""

    def __init__(self):
        self._i = 0

    def route(self, tokens, replicas: List[Replica],
              adapter=None) -> Replica:
        self._require(replicas)
        r = replicas[self._i % len(replicas)]
        self._i += 1
        return r
