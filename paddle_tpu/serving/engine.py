"""ServingEngine: continuous-batching inference over the paged-KV kernels.

The XLA-shaped answer to Orca/vLLM/SGLang-style serving: iteration-level
scheduling, block-based KV management and the radix prefix cache run on
the host (scheduler.py / kv_cache.py / radix_cache.py), while all device
work funnels through a SMALL, FIXED set of compiled programs — one per
shape bucket — so continuous batching never triggers unbounded
recompilation:

  * prefill CHUNK program, keyed by (chunk-length bucket, block-table
    bucket): processes one span of ONE padded prompt through
    `model.forward_paged_prefill` — rope at absolute positions,
    `paged_cache_write_range` at the chunk's offset, attention over the
    gathered paged prefix — and samples a token from the chunk's last
    live position (used only when the chunk completes the prompt).
    Whole-prompt prefill, chunked prefill, and radix prefix-cache hits
    are all THIS ONE program: a hit just starts at cache_len = matched
    tokens, so cache on/off cannot change program shapes (the
    determinism contract, SERVING.md);
  * decode program, keyed by (batch bucket, block-table-width bucket):
    one batched step through `model.forward_paged_decode` — per-row rope
    positions, `paged_cache_write` of the current token, Pallas
    `paged_attention_decode` over the block tables — plus sampling;
  * VERIFY program (speculative decoding, ISSUE 5), keyed by
    ("verify", batch bucket, draft-length bucket, block-table bucket):
    when a `Proposer` is configured, the decode launch is replaced by
    `model.forward_paged_verify` — each row scores its last emitted
    token plus up to K drafted tokens in ONE launch, acceptance is
    resolved in-graph (greedy longest-prefix match, or exact one-hot
    rejection sampling for temperature > 0), and rejected drafts' KV
    pages roll back via `BlockAllocator.truncate_sequence`. K rides the
    program key like B and P, so the compile bound stays the bucket
    grid (`max_program_count`);
  * MULTI_DECODE program (multi-step decode, ISSUE 13), keyed by
    ("multi_decode", batch bucket, steps bucket, block-table bucket):
    with `decode_steps=K` (no proposer), the decode launch runs K
    iterations of the decode body inside ONE compiled `lax.scan`
    (`model.forward_paged_decode_multi`) — in-graph sampling on
    per-step keys folded from one pre-drawn key, per-step paged cache
    writes through the loop carry, and per-row EOS/step-cap/finiteness
    masks that freeze completed rows — so each emitted token stops
    paying the ~7 ms host round trip. K rides the program key exactly
    like the verify program's.

Shape buckets pad up: a 19-token chunk runs in the 32-bucket, a decode
batch of 5 in the 8-bucket. The recompile counter (metrics) is bounded
by the bucket grid, which the engine test asserts.

Resilience layer (ISSUE 3, SERVING.md "Failure semantics"): per-request
deadlines/TTL and client `abort()`, cancelled at the next iteration
boundary in any state with valid KV donated to the radix cache;
bounded-queue admission control (`EngineOverloaded`); every compiled
launch runs under a `StepSupervisor` that retries transient device
errors with capped backoff, quarantines NaN-poisoned requests (each
program returns per-row finiteness flags computed in-graph — the jit
counterpart of the eager dispatch NaN hooks), and on unrecoverable
errors drains to a serializable snapshot a fresh engine resumes from
(`ServingEngine.from_snapshot`).

Determinism contract: greedy decode is deterministic, and a request's
tokens are bit-identical whether it runs alone or batched with others,
and whether its prefix came from the radix cache or its own prefill —
PROVIDED the same shape buckets are hit (XLA does not promise identical
rounding across different program shapes; rows within one program are
independent). The acceptance tests pin single buckets for exactly this
reason. Sampled decode draws from one engine-level key stream (final
chunks and decode steps draw; non-final chunks do not) and is
reproducible per (engine seed, arrival order) but not across different
interleavings.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..jit.api import functional_call
from ..models.generation import _filter_logits, _sample_arr
from ..utils import faults
from ..utils.nan_inf import poison_scope
from .errors import (EngineFailure, EngineOverloaded,
                     SnapshotVersionError, check_feature_conflicts)
from .lora.adapter import AdapterNotLoaded
from .kv_cache import (BlockAllocator, BlocksExhausted, HostPageCorrupt,
                       HostPageLost, HostPagesExhausted, HostPageSlow,
                       HostPageStore, PAD_PAGE, decode_page_payload,
                       encode_page_payload)
from .metrics import ServingMetrics
from .program_cache import ProgramCache
from .radix_cache import RadixCache
from .scheduler import (Request, RequestState, Scheduler,
                        bump_request_counter)
from .supervisor import POISON, RetryPolicy, StepSupervisor, classify_failure
from .trace import FlightRecorder, RequestTracer

__all__ = ["ServingEngine", "SNAPSHOT_VERSION", "SNAPSHOT_MINOR",
           "check_snapshot_version", "tp_serving_mesh"]


def tp_serving_mesh(tp: int, devices=None):
    """The hybrid [data, pipe, sharding, sep, model] mesh a TP serving
    engine wants: model degree `tp` over the first `tp` devices (or an
    explicit device list). Thin wrapper over fleet's build_mesh so the
    axis names can never drift from the training stack's."""
    import jax as _jax
    from ..distributed.fleet.topology import build_mesh
    if devices is None:
        devices = _jax.devices()[:int(tp)]
    return build_mesh(mp=int(tp), devices=devices)

_engine_counter = itertools.count()

# Injectable monotonic timer for the per-launch TPOT samples (ISSUE 13):
# the drift tests monkeypatch this module attribute to pin launch
# durations; everything else sees time.perf_counter.
_perf_counter = time.perf_counter

SNAPSHOT_VERSION = 1
# Forward-compat MINOR (ISSUE 14): bumped when a build ADDS snapshot
# fields that older builds can safely ignore. A rolling restart mixes
# worker versions, so adoption must accept a same-major snapshot from
# a NEWER minor — unknown extra top-level keys warn-and-ignore instead
# of failing; only a MAJOR mismatch (a schema this build would
# misread) stays the loud, typed refusal.
# minor 2 (ISSUE 15): request records carry an "adapter" field; a
# lora-aware adopter REQUIRES the adapter loaded (typed refusal — never
# wrong-adapter), while pre-lora builds ignore the key.
# minor 3 (ISSUE 18): request records carry a "colocate" flag — a
# supervisor-pinned request that a prefill-role engine must decode
# locally instead of handing off (role-starved fallback); role-less
# builds ignore it.
SNAPSHOT_MINOR = 3
_SNAPSHOT_KNOWN_KEYS = frozenset(
    {"version", "minor", "reason", "rng_key", "requests",
     "flight_recorder"})


def check_snapshot_version(snapshot: dict):
    """Refuse a snapshot whose schema `version` stamp is not the one
    this build writes. Used by `from_snapshot` AND by the fleet's live
    migration — both must fail LOUD (typed) instead of resuming a
    schema they would silently misread. Same-major snapshots from a
    NEWER minor (extra fields) are accepted with a warning — the
    rolling-restart mixed-version case."""
    found = snapshot.get("version")
    if found != SNAPSHOT_VERSION:
        raise SnapshotVersionError(
            f"unsupported snapshot version {found!r} (this build "
            f"writes {SNAPSHOT_VERSION})",
            found=found, expected=SNAPSHOT_VERSION)
    minor = snapshot.get("minor", 0)
    extra = sorted(set(snapshot) - _SNAPSHOT_KNOWN_KEYS)
    if extra or (isinstance(minor, int) and minor > SNAPSHOT_MINOR):
        import warnings
        warnings.warn(
            f"snapshot from a newer same-major build (minor {minor!r} "
            f"vs {SNAPSHOT_MINOR}); ignoring unknown keys {extra}",
            RuntimeWarning, stacklevel=2)

# Fault-injection points (ISSUE 3; utils/faults.py). The step-exception
# points fire BEFORE the compiled launch, so an injected transient
# retries the identical, not-yet-executed launch; nan_logits poisons the
# per-row finiteness flags AFTER the launch (the in-graph isfinite check
# is exercised for real by tests that NaN a weight); deadline_storm
# returns seconds of forward clock skew applied at the next boundary.
FAULT_CHUNK = faults.register_point("serving.engine.prefill_chunk")
FAULT_DECODE = faults.register_point("serving.engine.decode_step")
FAULT_NAN = faults.register_point("serving.engine.nan_logits")
FAULT_STORM = faults.register_point("serving.engine.deadline_storm")
# Speculative decoding (ISSUE 5): verify_step mirrors decode_step (fires
# BEFORE the verify launch — an injected transient retries the identical
# program); draft_storm replaces the proposer's drafts with the payload
# (callable(reqs, k) -> drafts, or True for seeded garbage) — the
# mismatch storm MUST be output-invariant under greedy acceptance, which
# the soak asserts. nan_logits covers the verify path too.
FAULT_VERIFY = faults.register_point("serving.engine.verify_step")
FAULT_DRAFT = faults.register_point("serving.spec.draft_storm")
# Multi-step decode (ISSUE 13): mirrors decode_step — fires BEFORE the
# launch, so an injected transient retries the identical K-step program.
FAULT_MULTI = faults.register_point("serving.engine.multi_decode_step")

# Ceiling on decode_steps (K): each launch runs K decode iterations in
# one device-side scan, and device loops past ~512 iterations have
# wedged the chip over this transport (the tpu-lint A4 wedge cap,
# kernels/timing.py lesson). 64 leaves an order of magnitude of
# headroom while still amortizing the ~7 ms host round trip ~64x.
MAX_DECODE_STEPS = 64


def _bucket_for(value: int, buckets: List[int]) -> int:
    for b in buckets:
        if value <= b:
            return b
    raise ValueError(f"{value} exceeds largest bucket {buckets[-1]}")


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


class _HostSpillBridge:
    """RadixCache.spill implementation over ONE engine's device caches
    and its HostPageStore (protocol: RadixCache.__init__). The tree
    stays device-blind; all array traffic funnels through here.

    demote() gathers each device page's rows across every layer into
    one encoded payload (a real device->host fetch per array — the
    eviction path already tolerates host latency); promote() decodes
    every payload FIRST (a corrupt page must fail before any device
    page is claimed), then allocates device pages and enqueues per-
    layer `.at[pid].set(...)` scatters WITHOUT a host sync — jax
    dispatch is async, so the copies overlap the prefill launch the
    scheduler is about to build, and the device stream orders them
    before any kernel that reads the pages (the "in-flight" residency
    window is exactly this enqueued-not-fetched state).
    """

    def __init__(self, engine: "ServingEngine"):
        self.eng = engine

    def host_free(self) -> int:
        return self.eng.host_store.num_free

    def holds(self, hid: int) -> bool:
        return self.eng.host_store.holds(hid)

    def demote(self, pids):
        """Device pages -> host payloads. Returns the host ids, or None
        when the host pool ran out mid-batch (partial puts roll back, so
        a refused demotion leaks nothing — the caller drops instead)."""
        store = self.eng.host_store
        hids = []
        try:
            for pid in pids:
                hids.append(store.put(
                    self.eng._gather_page_payload(pid)))
        except HostPagesExhausted:
            for hid in hids:
                store.decref(hid)
            return None
        return hids

    def promote(self, hids):
        """Host payloads -> fresh device pages (refcount 1 each — the
        tree ref). Returns None when the device pool is dry (recompute
        beats evicting for a maybe-hit); HostPageError kinds propagate
        AFTER the fault counter bump, with no device page claimed."""
        eng = self.eng
        c = eng.metrics.counters
        payloads = []
        try:
            for hid in hids:
                payloads.append(
                    decode_page_payload(eng.host_store.get(hid)))
        except HostPageSlow:
            c["host_spill_slow"] += 1
            raise
        except HostPageCorrupt:
            c["host_spill_corrupt"] += 1
            raise
        except HostPageLost:
            c["host_spill_lost"] += 1
            raise
        try:
            pids = eng.allocator._alloc_pages(len(hids))
        except BlocksExhausted:
            return None
        for pid, arrays in zip(pids, payloads):
            eng._scatter_page_payload(pid, arrays)
        return pids

    def release(self, hids):
        """Drop the tree's host refs. Tolerates ids the store forgot
        after a host_spill.lost fault — the lost slot is already free,
        and a decref there would double-free a reused slot."""
        store = self.eng.host_store
        for hid in hids:
            if store.holds(hid):
                store.decref(hid)


class ServingEngine:
    """Continuous-batching engine over a causal LM with paged-KV decode.

    model: a LlamaForCausalLM-protocol model — `forward_paged_prefill`
    for (chunked) prompt processing and `forward_paged_decode` for the
    batched decode step, both over the engine-owned paged caches
    (plus `forward_paged_verify` when speculative decoding is on).
    enable_prefix_cache turns the radix tree on (default); off, the
    engine behaves like PR 1 plus chunked prefill.

    proposer (serving.spec.Proposer, optional) enables speculative
    decoding: up to `spec_k` draft tokens per decoding request are
    verified per step in one ("verify", B, K, P) launch; greedy output
    is token-identical to plain decode (drafting only changes how many
    launches it takes), and `spec_buckets` is the K axis of the
    program grid.

    decode_steps=K (ISSUE 13) runs K decode iterations inside ONE
    compiled ("multi_decode", B, K, P) launch — a device-side scan
    over the decode body with in-graph sampling, per-step paged cache
    writes, and per-row EOS/max-token/finiteness masks that freeze
    completed rows — so each emitted token stops paying the ~7 ms
    host round trip. Greedy output is token-identical to K=1 (the
    per-step math is the same program body; rows are independent);
    the scheduler admits/preempts at K-step boundaries and the decode
    token budget is charged xK; abort/TTL take effect at the next
    K-boundary with the launch's tokens delivered; NaN quarantine is
    per LAUNCH (a poisoned row delivers none of the launch's tokens).
    Mutually exclusive with `proposer` — both multiply tokens per
    launch. `multi_buckets` is the K axis of the program grid.

    Quantized decode path (ISSUE 6):
    * kv_dtype="int8" stores KV pages as int8 with fp32 per-slot
      scales riding the SAME page ids (quantize-on-write inside the
      compiled programs, dequantize-in-kernel/-gather on read) — the
      page payload halves, so at a fixed `kv_pool_bytes` the pool
      holds ~2x the pages (2D/(D+4) exactly; paged_page_bytes is the
      math's single source). All page bookkeeping (CoW fork, radix
      donation, truncate_sequence rollback, snapshot/resume) is
      host-side and byte-level, so it is bit-identical across
      kv_dtype — only the attention arithmetic changes, within the
      documented rel-err budget.
    * wq="int8" converts the model's decode-regime projections
      (MLP gate/up/down + LM head) to int8 weights IN PLACE
      (nn.quant.quantize_for_serving) before the state snapshot, so
      every program serves them through the fused Pallas
      dequant-matmul (kernels/quant_matmul.py). The conversion
      mutates `model` — pass a model dedicated to this engine.
    * kv_pool_bytes sizes num_pages from an HBM byte budget instead
      of a page count (num_pages = budget // page_bytes) — the knob
      the capacity-doubling acceptance test turns.
    Both ride the program-cache keys, so engines with different quant
    configs sharing a process never collide, and the compile bound
    stays the bucket grid.

    Multi-LoRA serving (ISSUE 15): pass `lora` (a
    serving.lora.AdapterRegistry built for this model's dims) and tag
    requests with `add_request(adapter=...)`. Adapter A/B factors live
    PAGED in the registry's device pools (BlockAllocator discipline,
    LRU eviction of idle adapters, live-request refcount pinning);
    every program takes the pools/page-tables/per-row slot ids as
    call-time INPUTS, gathers the fixed-shape slot stacks in-graph and
    applies each row's own delta through the masked segment-bmm kernel
    (kernels/lora_matmul.py) — rows of one launch may mix adapters,
    load/unload never recompiles, and only the static layout signature
    rides the program key. The radix key is adapter-namespaced
    (prefixes never cross adapters) and snapshots carry the adapter
    (adoption requires it loaded — typed refusal otherwise). Mutually
    exclusive with `proposer` and `mesh` (documented in SERVING.md).

    Tensor-parallel serving (ISSUE 8): pass `mesh` (a hybrid
    [data, pipe, sharding, sep, model] jax Mesh with model degree tp)
    to shard attention heads, the paged KV pool (page CONTENTS,
    including int8 scale pages — page IDS stay global) and the
    MLP/LM-head weights over 'model'. The scheduler, BlockAllocator
    and RadixCache are host-side and rank-replicated, so every
    paging/refcount/radix trace is bit-identical to the single-chip
    engine by construction; all three program families compile under
    jax.jit with GSPMD shardings (column-parallel QKV/gate-up,
    row-parallel O/down with psum, paged attention per shard over its
    own KVH/tp kv heads — kernels.paged_attention_decode_tp), and the
    mesh shape rides the program-cache key. `kv_pool_bytes` stays a
    PER-CHIP budget: head-sharded pages cost kv_page_bytes_shard per
    chip, so capacity at fixed per-chip bytes scales ~x tp.
    """

    def __init__(self, model, *, num_pages: int = 128, page_size: int = 16,
                 max_batch_size: int = 8, token_budget: int = 512,
                 batch_buckets: Optional[List[int]] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 pages_buckets: Optional[List[int]] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 max_retained_finished: int = 1024,
                 enable_prefix_cache: bool = True,
                 max_queue_len: Optional[int] = None,
                 default_ttl_s: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 clock=None,
                 proposer=None, spec_k: int = 4,
                 spec_buckets: Optional[List[int]] = None,
                 decode_steps: int = 1,
                 multi_buckets: Optional[List[int]] = None,
                 kv_dtype: Optional[str] = None,
                 wq: Optional[str] = None,
                 kv_pool_bytes: Optional[int] = None,
                 host_spill_pages: int = 0,
                 mesh=None,
                 lora=None,
                 role: str = "both",
                 compile_cache=None,
                 trace=None, trace_ring: int = 512,
                 flight_recorder_steps: int = 128):
        cfg = model.cfg
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', got "
                             f"{kv_dtype!r}")
        if wq not in (None, "int8", "int4"):
            raise ValueError(f"wq must be None, 'int8' or 'int4', got "
                             f"{wq!r}")
        self.kv_dtype = kv_dtype
        self.wq = wq
        # --- disaggregated serving role (ISSUE 18) ---
        # "both" (default) is the co-located engine. "prefill": every
        # request that completes its prefill finishes with reason
        # "handoff" instead of entering the decode batch — its
        # block-aligned pages sit donated in the radix tree for the
        # fleet's kv_pull, and `handoff_prefix_len` on the request
        # records the span; requests adopted with a "colocate" pin
        # decode locally anyway (role-starved fallback). "decode" is a
        # routing tag only — the engine behaves exactly like "both"
        # (it must re-prefill prompt tails and failed handoffs).
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be 'both', 'prefill' or "
                             f"'decode', got {role!r}")
        self.role = role
        # --- tensor parallelism (ISSUE 8) ---
        # mesh: a hybrid [data, pipe, sharding, sep, model] jax Mesh (or
        # any mesh with a 'model' axis). Attention heads, the paged KV
        # pool's page CONTENTS (including int8 scale pages) and the
        # MLP/LM-head weights shard over 'model'; the scheduler,
        # BlockAllocator and RadixCache stay host-side and
        # rank-replicated — page IDS are global, so every paging/
        # refcount/radix decision is bit-identical to the single-chip
        # engine by construction.
        self.mesh = mesh
        self.tp = (int(dict(mesh.shape).get("model", 1))
                   if mesh is not None else 1)
        if self.tp > 1:
            if cfg.num_key_value_heads % self.tp:
                raise ValueError(
                    f"num_key_value_heads {cfg.num_key_value_heads} not "
                    f"divisible by model-axis degree {self.tp}")
            if cfg.num_attention_heads % self.tp:
                raise ValueError(
                    f"num_attention_heads {cfg.num_attention_heads} not "
                    f"divisible by model-axis degree {self.tp}")
        # the central capability table (serving/errors.py, ROADMAP item
        # 4): every pairwise feature conflict is ONE check against ONE
        # table — the scattered per-feature raises this replaces could
        # (and did) drift apart as features landed in different PRs
        active = set()
        if proposer is not None:
            active.add("proposer")
        if int(decode_steps) > 1:
            active.add("multi_step_decode")
        if lora is not None:
            active.add("lora")
        if self.tp > 1:
            active.add("tensor_parallel")
        if int(host_spill_pages) > 0:
            active.add("host_spill")
        if not enable_prefix_cache:
            active.add("no_prefix_cache")
        if role == "prefill":
            active.add("prefill_role")
        check_feature_conflicts(active)
        if wq is not None:
            # IN PLACE, before the state snapshot below: the quantized
            # buffers (int8 qweight + fp scale) replace the fp weights
            # in state_dict, so every compiled program reads 1 byte per
            # weight element through the fused dequant-matmul
            from ..nn.quant import quantize_for_serving
            self.num_wq_layers = quantize_for_serving(
                model, algo=f"weight_only_{wq}")
        else:
            self.num_wq_layers = 0
        self.model = model
        self.cfg = cfg
        self.num_layers = cfg.num_hidden_layers
        self.num_kv = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.page_size = int(page_size)
        from ..kernels.paged_attention import paged_page_bytes
        wdtype = next(t._data.dtype for t in model.state_dict().values()
                      if jnp.issubdtype(t._data.dtype, jnp.floating))
        # bytes one page costs in THIS engine (int8 pages + scales, or
        # the model dtype's full-width pages) — the capacity gauge and
        # the kv_pool_bytes sizing below both hang off it. Under TP a
        # page's contents are head-sharded, so one chip pays only the
        # per-SHARD bytes (KVH/tp heads) — both numbers come from the
        # same paged_page_bytes source (linear in KVH, so
        # shard * tp == global exactly)
        self._kv_dtype_name = (kv_dtype if kv_dtype is not None
                               else str(wdtype))
        self.kv_page_bytes = paged_page_bytes(
            cfg.num_key_value_heads, self.page_size, self.head_dim,
            self._kv_dtype_name)
        self.kv_page_bytes_shard = paged_page_bytes(
            cfg.num_key_value_heads // self.tp, self.page_size,
            self.head_dim, self._kv_dtype_name)
        if kv_pool_bytes is not None:
            # size the pool from a PER-CHIP HBM byte budget: the page
            # count is what kv_dtype="int8" roughly doubles and TP
            # multiplies by ~tp at fixed per-chip bytes (head-sharded
            # pages cost kv_page_bytes_shard per chip)
            num_pages = max(2, int(kv_pool_bytes)
                            // self.kv_page_bytes_shard)
        self.num_pages = int(num_pages)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self._key = jax.random.PRNGKey(seed)
        # non-final chunks pass a fixed key (their sampled token is
        # discarded) so the engine's key stream advances once per token
        # actually emitted, not once per chunk
        self._null_key = jax.random.PRNGKey(0)

        # serving weights are immutable: snapshot the flat {name: array}
        # view once instead of re-walking state_dict() every step.
        # Under TP each weight is device_put per its mark_sharding spec
        # (column-parallel QKV/gate-up split the out dim, row-parallel
        # O/down the in dim, the vocab embedding its vocab dim);
        # spec-less buffers (rope tables, quant scales without an out
        # shard) replicate. jit then reads the argument shardings — no
        # per-weight constraints needed inside the programs.
        self._state = {}
        for k, t in model.state_dict().items():
            self._state[k] = self._place(t._data,
                                         getattr(t, "_spec", None))

        # fail at construction, not at the first decode launch: the
        # Pallas kernel's static constraints are model geometry — under
        # TP the kernel sees the PER-SHARD geometry (H/tp query heads
        # over KVH/tp kv heads), so that is what must be legal
        from ..kernels.paged_attention import check_supported_paged
        dtype = next(a.dtype for a in self._state.values()
                     if jnp.issubdtype(a.dtype, jnp.floating))
        self._cache_dtype = jnp.int8 if kv_dtype == "int8" else dtype
        check_supported_paged(
            (1, cfg.num_attention_heads // self.tp, self.head_dim),
            (self.num_pages, self.num_kv // self.tp, self.page_size,
             self.head_dim),
            dtype, kv_dtype=kv_dtype)

        # longest sequence a request may ever reach (rope table and page
        # supply both bound it)
        self.max_seq_len = min(int(cfg.max_position_embeddings),
                               (self.num_pages - 1) * self.page_size)
        max_pages_per_seq = -(-self.max_seq_len // self.page_size)

        self.batch_buckets = sorted(batch_buckets or
                                    _pow2_buckets(1, int(max_batch_size)))
        self.prefill_buckets = sorted(
            prefill_buckets or _pow2_buckets(
                min(16, self.max_seq_len), self.max_seq_len))
        self.pages_buckets = sorted(
            pages_buckets or _pow2_buckets(
                min(2, max_pages_per_seq), max_pages_per_seq))
        # the widest block table a decode program supports also bounds
        # how long any sequence may grow
        self.max_seq_len = min(self.max_seq_len,
                               self.pages_buckets[-1] * self.page_size)
        if self.prefill_buckets[-1] > self.max_seq_len:
            raise ValueError("prefill bucket exceeds max sequence length")

        # --- speculative decoding (ISSUE 5) ---
        # proposer drafts up to spec_k tokens per decoding request per
        # step; the bucketed ("verify", B, K, P) program scores them in
        # one launch. K rides the program-cache KEY (like B and P), so
        # the compile count stays bounded by the grid — spec_buckets is
        # the K axis of that grid.
        self.proposer = proposer
        self.spec_k = int(spec_k)
        if proposer is not None and self.spec_k < 1:
            raise ValueError("spec_k must be >= 1 with a proposer")
        self.spec_buckets = sorted(
            spec_buckets or _pow2_buckets(1, max(1, self.spec_k))) \
            if proposer is not None else []
        if self.spec_buckets and self.spec_buckets[-1] != self.spec_k:
            raise ValueError(
                f"largest spec bucket {self.spec_buckets[-1]} must equal "
                f"spec_k {self.spec_k}")

        # --- multi-step decode (ISSUE 13) ---
        # decode_steps=K runs K decode iterations inside ONE compiled
        # ("multi_decode", B, K, P) launch (lax.scan over the decode
        # body, in-graph sampling + per-row freeze masks) — the plain-
        # decode counterpart of the verify program. K rides the
        # program-cache key with multi_buckets as its grid axis, so the
        # compile bound stays the bucket grid. Mutually exclusive with
        # speculative decoding per launch: both multiply tokens per
        # launch and would double-charge the token budget.
        self.decode_steps = int(decode_steps)
        if self.decode_steps < 1:
            raise ValueError("decode_steps must be >= 1")
        if self.decode_steps > MAX_DECODE_STEPS:
            raise ValueError(
                f"decode_steps {self.decode_steps} exceeds "
                f"MAX_DECODE_STEPS {MAX_DECODE_STEPS} (device-side loop "
                f"trip counts are capped well under the 512-iteration "
                f"wedge cap — tpu-lint A4)")
        # decode_steps x proposer conflicts via the capability table
        # (checked above — serving/errors.py FEATURE_CONFLICTS)
        self.multi_buckets = sorted(
            multi_buckets or _pow2_buckets(1, self.decode_steps)) \
            if self.decode_steps > 1 else []
        if self.multi_buckets and self.multi_buckets[-1] != self.decode_steps:
            raise ValueError(
                f"largest multi bucket {self.multi_buckets[-1]} must "
                f"equal decode_steps {self.decode_steps}")

        # --- multi-LoRA adapter serving (ISSUE 15) ---
        # lora: an AdapterRegistry (serving.lora). Requests carry an
        # adapter NAME (`add_request(adapter=...)`); each launch passes
        # the registry's paged pools + page tables + per-row slot ids
        # as program INPUTS and the programs gather/apply each row's
        # own adapter delta in-graph — rows of one launch may mix
        # adapters, and load/unload/evict never recompiles (only the
        # static layout signature rides the program key, below).
        self.lora = lora
        # lora x proposer / lora x tensor_parallel conflicts via the
        # capability table (checked above)

        self.allocator = BlockAllocator(self.num_pages, self.page_size)
        self.radix = (RadixCache(self.allocator)
                      if enable_prefix_cache else None)
        self.scheduler = Scheduler(
            self.allocator, max_batch_size=self.batch_buckets[-1],
            token_budget=min(token_budget, self.prefill_buckets[-1]),
            max_prompt_len=self.max_seq_len,
            prefix_cache=self.radix,
            max_queue_len=max_queue_len)
        if proposer is not None:
            # verify tokens draw from the same per-step token budget
            # prefill chunks compete for (SERVING.md bucketing note)
            self.scheduler.decode_token_cost = 1 + self.spec_k
        elif self.decode_steps > 1:
            # each decoding request may emit up to K tokens per launch:
            # charge the budget xK so admission/preemption decisions at
            # K-step boundaries see the true per-launch token traffic
            self.scheduler.decode_token_cost = self.decode_steps
        # --- resilience (ISSUE 3) ---
        # deadlines use an injectable clock (tests/soak pass a fake one;
        # the fault harness adds skew) so expiry stays deterministic
        self._clock = clock if clock is not None else time.monotonic
        self._clock_skew = 0.0
        self.default_ttl_s = default_ttl_s
        self.supervisor = StepSupervisor(
            policy=retry_policy,
            on_retry=self._on_step_retry,
            retryable=self._caches_alive)
        self.failed = False
        self.last_snapshot: Optional[dict] = None
        # per-engine provider name: two live engines must not shadow each
        # other in profiler.counters(), nor unregister each other
        self.metrics = ServingMetrics(
            name=f"serving-{next(_engine_counter)}").register()
        if self.lora is not None:
            # registry lifecycle counters land in THIS engine's
            # auto-exposed metrics (loads done before attach carry in)
            self.lora.bind_counters(self.metrics.counters)
        # --- observability (ISSUE 10) ---
        # Per-request tracing is OFF by default and free when off:
        # every hook is guarded by ONE `self.tracer is None` check, so
        # the default hot path allocates nothing trace-related.
        # trace=True builds a private RequestTracer; a fleet passes the
        # SAME RequestTracer instance to every replica so a migrated
        # request keeps one trace across engines. The flight recorder
        # is always on — one small dict per non-idle step, bounded ring
        # — and rides every snapshot so postmortems carry context.
        if trace is True:
            self.tracer: Optional[RequestTracer] = RequestTracer(
                max_completed=trace_ring)
        elif trace:
            self.tracer = trace
        else:
            self.tracer = None
        self.recorder = FlightRecorder(flight_recorder_steps)
        self._cur_rids = ()          # requests in the launch being run
        self._step_ev = {"programs": []}
        self._step_t0: Optional[float] = None
        self._last_launch_s: Optional[float] = None

        from jax.sharding import PartitionSpec as P
        shape = (self.num_pages, self.num_kv, self.page_size, self.head_dim)
        # page contents head-sharded over 'model' (page IDS stay
        # global): one chip holds KVH/tp heads of every page
        kv_spec = P(None, "model", None, None) if self.tp > 1 else None
        sc_spec = P(None, "model", None) if self.tp > 1 else None
        self._k_caches = [self._place(jnp.zeros(shape, self._cache_dtype),
                                      kv_spec)
                          for _ in range(self.num_layers)]
        self._v_caches = [self._place(jnp.zeros(shape, self._cache_dtype),
                                      kv_spec)
                          for _ in range(self.num_layers)]
        if self.kv_dtype == "int8":
            from ..kernels.paged_attention import KV_SCALE_DTYPE
            self._k_scales = [self._place(
                jnp.zeros(shape[:3], KV_SCALE_DTYPE), sc_spec)
                for _ in range(self.num_layers)]
            self._v_scales = [self._place(
                jnp.zeros(shape[:3], KV_SCALE_DTYPE), sc_spec)
                for _ in range(self.num_layers)]
        else:
            # empty pytrees: the compiled programs take the scale lists
            # unconditionally so both kv_dtypes share one program shape
            self._k_scales = []
            self._v_scales = []
        # bytes-moved accounting (ServingMetrics): one token's K+V
        # across every layer, scales included — GLOBAL bytes (the sum
        # over shards); per-chip traffic is this / tp
        self.kv_bytes_per_token = (self.num_layers * self.kv_page_bytes
                                   // self.page_size)
        self.metrics.set_kv_info(
            kv_dtype=self.kv_dtype or str(dtype),
            page_bytes=self.kv_page_bytes,
            pool_bytes=self.kv_page_bytes * self.num_pages,
            bytes_per_token=self.kv_bytes_per_token,
            tp_degree=self.tp,
            page_bytes_shard=self.kv_page_bytes_shard,
            pool_bytes_shard=self.kv_page_bytes_shard * self.num_pages)

        # --- tiered KV: host-RAM spill tier (ISSUE 17) ---
        # host_spill_pages > 0 puts a HostPageStore under the radix
        # cache: LRU eviction DEMOTES pages (values + int8 scale rows)
        # to host payloads instead of freeing them, and a later match
        # PROMOTES them back with an async host->device copy overlapped
        # with the prefill launch. 0 (the default) is bit-for-bit the
        # pre-spill engine. One host page carries a radix page's K+V
        # across EVERY layer (scales included): num_layers x
        # kv_page_bytes — the whole per-layer stack is the demote unit.
        self.host_spill_pages = int(host_spill_pages)
        if self.host_spill_pages < 0:
            raise ValueError("host_spill_pages must be >= 0")
        # host_spill x tensor_parallel / x no_prefix_cache conflicts
        # via the capability table (checked above)
        self.host_page_bytes = self.num_layers * self.kv_page_bytes
        if self.host_spill_pages:
            self.host_store: Optional[HostPageStore] = HostPageStore(
                self.host_spill_pages)
            self.radix.set_spill(_HostSpillBridge(self))
            self.metrics.set_host_info(
                pool_pages=self.host_spill_pages,
                page_bytes=self.host_page_bytes)
        else:
            self.host_store = None

        self.requests: Dict[int, Request] = {}
        self._finished_order: List[int] = []
        # a long-lived server must not accumulate every finished request
        # (same unbounded-growth class as the jit fallback registry):
        # only the most recent `max_retained_finished` stay readable
        self.max_retained_finished = int(max_retained_finished)
        self.num_evicted_finished = 0
        # the unified ProgramCache (ISSUE 8): one keyed store for the
        # chunk/decode/verify families with per-family bucket-grid
        # bounds (whole-prompt prefill and chunked prefill are ONE
        # family — the chunk program — so "prefill" compiles count
        # under "chunk" by design; the draft-model proposer runs its
        # own cache with its own families)
        self.programs = ProgramCache(
            on_compile=lambda: self.metrics.on_recompile())
        self.programs.register_family(
            "chunk", lambda: (len(self.prefill_buckets)
                              * len(self.pages_buckets)))
        self.programs.register_family(
            "decode", lambda: (len(self.batch_buckets)
                               * len(self.pages_buckets)))
        self.programs.register_family(
            "verify", lambda: (len(self.batch_buckets)
                               * len(self.spec_buckets)
                               * len(self.pages_buckets)))
        self.programs.register_family(
            "multi_decode", lambda: (len(self.batch_buckets)
                                     * len(self.multi_buckets)
                                     * len(self.pages_buckets)))
        # caches only pay off donated on a real accelerator; CPU jit
        # warns per call and keeps the copy anyway. Scale lists donate
        # too (empty pytrees for full-width KV — a no-op there).
        self._donate = (1, 2, 3, 4) if jax.default_backend() == "tpu" \
            else ()
        # quant config AND the mesh shape ride every program-cache key:
        # two engines with different kv_dtype/wq/TP degree in one
        # process must never share a compiled program, and the
        # bucket-grid compile bound is per-engine (one mesh shape per
        # engine) so the key suffix costs nothing. The sampling config
        # rides too (B1): temperature/top_k/top_p are closed over as
        # Python constants by every builder, so without the key axis a
        # persistent CompileCache entry written at one temperature
        # would be served to a restarted worker running another
        self._qkey = (self.kv_dtype or "kv_full", self.wq or "w_full",
                      ("tp", self.tp),
                      ("sampling", self.temperature, self.top_k,
                       self.top_p))
        if self.lora is not None:
            # the STATIC lora layout (slots x rank buckets x page
            # geometry) rides every program key; adapter ids never do
            # — loading/unloading adapters can never grow the grid
            self._qkey = self._qkey + (self.lora.signature(),)

        # --- persistent compile cache (ISSUE 14) ---
        # compile_cache: a directory path (a CompileCache is built over
        # it, fingerprinted with THIS engine's model/pool geometry) or
        # a ready CompileCache instance (caller owns the fingerprint —
        # sharing one instance across engines also shares its
        # counters). Misses in the ProgramCache then consult disk
        # before building, and `save_compile_cache()` persists every
        # launched program so a restarted worker skips the bucket-grid
        # compile storm.
        if compile_cache is not None:
            from .compile_cache import CompileCache
            if not isinstance(compile_cache, CompileCache):
                compile_cache = CompileCache(
                    str(compile_cache), extra=self._geometry_signature())
            self.programs.disk = compile_cache
        self._sync_compile_cache_counters()

    def _caches_alive(self) -> bool:
        """Retry gate for the donated-buffer hazard: on TPU the compiled
        programs donate the K/V caches (`donate_argnums`), and a launch
        that failed AFTER the dispatch consumed them leaves deleted
        arrays behind — re-passing those would raise, so the supervisor
        must fail over to the snapshot path instead of retrying. On CPU
        (donation off) and for failures raised BEFORE dispatch (fault
        injection, relay connect errors) the buffers stay alive and
        retries proceed."""
        probe = (self._k_caches[0], self._v_caches[0])
        return not any(getattr(a, "is_deleted", lambda: False)()
                       for a in probe)

    # ----------------------------------------- request tracing (ISSUE 10)
    # Every hook no-ops on `self.tracer is None` — the ONE check the
    # default (trace-off) hot path pays; nothing below it allocates.
    def _on_step_retry(self, label: str, attempt: int):
        self.metrics.on_step_retry()
        if self.tracer is not None:
            for rid in self._cur_rids:
                self.tracer.mark(rid, "retry", label=label,
                                 attempt=attempt,
                                 engine=self.metrics.name)

    def _tr_begin(self, req: Request):
        if self.tracer is None:
            return
        self.tracer.begin(req.request_id, engine=self.metrics.name,
                          prompt_len=len(req.prompt_ids),
                          max_new_tokens=req.max_new_tokens)

    def _tr_shed(self, req: Request):
        """Admission shed: the trace begins and ends at the door —
        sheds must be visible in the completed ring, not invisible."""
        if self.tracer is None:
            return
        self.tracer.begin(req.request_id, engine=self.metrics.name,
                          prompt_len=len(req.prompt_ids),
                          max_new_tokens=req.max_new_tokens)
        self.tracer.mark(req.request_id, "shed",
                         engine=self.metrics.name,
                         queue_depth=self.scheduler.queue_depth)
        self.tracer.finish(req.request_id, "shed")

    def _tr_admit(self, req: Request, resumed: bool):
        if self.tracer is None:
            return
        tr = self.tracer.get(req.request_id)
        if tr is None:
            return
        now = self.tracer.now_ns()
        tr.span("queue_wait", tr.t_queue, now, resumed=resumed)
        tr.mark("admitted", now, cached_tokens=req.cached_tokens,
                resumed=resumed, engine=self.metrics.name)

    def _tr_launch(self, rids, name: str, t0: int, **args):
        """One span per PARTICIPATING request for a batched launch —
        the per-request timeline view of shared device work. The args
        are identical across the batch, so the record is built once
        (`span_many`) — the traced decode hot path stays cheap."""
        if self.tracer is None:
            return
        self.tracer.span_many(rids, name, t0, self.tracer.now_ns(),
                              engine=self.metrics.name, **args)

    def _tr_mark(self, rid: int, name: str, **args):
        if self.tracer is None:
            return
        self.tracer.mark(rid, name, engine=self.metrics.name, **args)

    def _tr_finish(self, rid: int, reason: str):
        if self.tracer is None:
            return
        self.tracer.finish(rid, reason)

    def _tr_preempt(self, req: Request):
        if self.tracer is None:
            return
        tr = self.tracer.get(req.request_id)
        if tr is None:
            return
        now = self.tracer.now_ns()
        tr.mark("preempted", now, engine=self.metrics.name)
        tr.t_queue = now     # the next admission's queue_wait anchor

    # ------------------------------------------------------------- intake
    def _now(self) -> float:
        return self._clock() + self._clock_skew

    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None,
                    ttl_s: Optional[float] = None,
                    deadline: Optional[float] = None,
                    adapter: Optional[str] = None) -> int:
        """Queue one request. `ttl_s` (or an absolute engine-clock
        `deadline`) bounds its total lifetime: past it, the request is
        cancelled at the next iteration boundary whatever its state.
        Raises `EngineOverloaded` when the bounded waiting queue is full
        (admission control — shed at the door, never grow unbounded).

        `adapter` (ISSUE 15) names a LoRA adapter the registry must
        CURRENTLY hold — unknown/unloaded adapters shed typed
        (`AdapterNotLoaded`) at the door, never serve base weights by
        accident. An admitted request pins its adapter (registry
        refcount) until it reaches a terminal state, so LRU eviction
        can never take the weights out from under live work."""
        if self.failed:
            raise EngineFailure("engine has failed; resume from "
                                "last_snapshot", snapshot=self.last_snapshot)
        if adapter is not None:
            if self.lora is None:
                raise AdapterNotLoaded(
                    f"request names adapter {adapter!r} but this engine "
                    f"has no adapter registry (lora=None)",
                    adapter=adapter)
            if not self.lora.has(adapter):
                self.metrics.counters["adapter_rejects"] += 1
                raise AdapterNotLoaded(
                    f"adapter {adapter!r} is not loaded "
                    f"(loaded: {self.lora.adapter_names()})",
                    adapter=adapter)
        req = Request(prompt_ids, max_new_tokens, eos_token_id,
                      adapter=adapter)
        if len(req.prompt_ids) + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {len(req.prompt_ids)} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_seq_len "
                f"{self.max_seq_len}")
        # NOTE: PR 1 also rejected requests whose post-preemption resume
        # (prompt + max_new - 1) outsized the largest prefill bucket.
        # Chunked prefill removed that failure mode: a resume of any
        # length within max_seq_len re-prefills in budget-sized chunks.
        if ttl_s is None and deadline is None and \
                self.default_ttl_s is not None:
            ttl_s = self.default_ttl_s
        if ttl_s is not None and deadline is not None:
            raise ValueError("pass ttl_s or deadline, not both")
        if ttl_s is not None:
            deadline = self._now() + float(ttl_s)
        req.deadline = deadline
        try:
            self.scheduler.add_request(req)
        except EngineOverloaded:
            self.metrics.on_shed()
            self._tr_shed(req)
            raise
        if adapter is not None:
            self.lora.acquire(adapter)     # pinned until terminal
            # versioned radix namespace: a reload of the same name
            # must never match KV cached under the replaced weights
            req.adapter_key = self.lora.namespace_of(adapter)
        self.requests[req.request_id] = req
        self.metrics.on_add(req.request_id)
        self._tr_begin(req)
        return req.request_id

    def abort(self, request_id: int) -> bool:
        """Client abort: the request is cancelled at the next iteration
        boundary in whatever state it is in (queued, chunk-prefilling,
        decoding, or preempted), its valid KV donated to the radix
        cache. Returns False when the request is unknown or already
        finished."""
        req = self.requests.get(request_id)
        if req is None or req.state is RequestState.FINISHED:
            return False
        req.aborted = True
        return True

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    # ---------------------------------------------------- TP placement
    def _place(self, arr, spec):
        """device_put `arr` onto the engine mesh per `spec` (replicated
        when spec is None); identity without a mesh. Specs whose rank
        does not fit the array (a reshaped/stacked buffer) fall back to
        replication — correctness never depends on placement, only
        memory footprint does."""
        if self.mesh is None:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P
        try:
            return jax.device_put(
                arr, NamedSharding(self.mesh,
                                   spec if spec is not None else P()))
        except Exception:   # noqa: BLE001 — rank/divisibility mismatch
            return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def _trace_scope(self):
        """Context active around every program call: pins current_mesh()
        to the engine mesh so the mpu layers' GSPMD constraints (and the
        TP paged-attention route in models/llama.py) are live at trace
        time — without requiring fleet.init's process-global topology.
        A mesh-less engine pins mesh_scope(None), MASKING any ambient
        fleet.init mesh: otherwise a training process with mp>1 would
        leak its mesh into the serving trace and activate TP routing
        this engine never opted into (or validated divisibility for)."""
        from ..distributed.fleet.mpu import mesh_scope
        return mesh_scope(self.mesh)

    # ------------------------------------- multi-LoRA plumbing (ISSUE 15)
    def load_adapter(self, adapter, quant: Optional[str] = None) -> int:
        """Load a LoRAAdapter into the registry at runtime (no
        recompile — only page/table VALUES change). Returns the global
        launch slot. quant="int8" stores the payload quantized."""
        if self.lora is None:
            raise AdapterNotLoaded("engine has no adapter registry "
                                   "(construct with lora=...)")
        return self.lora.load(adapter, quant=quant)

    def unload_adapter(self, name: str):
        """Unload an IDLE adapter (typed AdapterBusy while live
        requests still pin it)."""
        if self.lora is None:
            raise AdapterNotLoaded("engine has no adapter registry "
                                   "(construct with lora=...)")
        self.lora.unload(name)

    def _lora_launch_args(self, reqs, B: int) -> tuple:
        """Per-launch lora program inputs: (row_slots (B,), *registry
        flat args) — empty when lora is off, so lora-less launch sites
        splat nothing. Padded batch rows map to global slot 0 (every
        bucket's null adapter -> exact zero delta)."""
        if self.lora is None:
            return ()
        rows = np.zeros((B,), np.int32)
        for i, r in enumerate(reqs):
            if r.adapter is not None:
                rows[i] = self.lora.slot_of(r.adapter)
        return (jnp.asarray(rows),) + self.lora.flat_args()

    def _lora_trace_scope(self, largs):
        """Scope entered INSIDE a traced program body, around the model
        call: builds the launch LoRAContext from the traced lora args
        and activates the projection hooks. Null context when off."""
        if self.lora is None or not largs:
            import contextlib
            return contextlib.nullcontext()
        from .lora.runtime import build_context, lora_scope
        return lora_scope(build_context(self.lora.layout, largs[1:],
                                        largs[0]))

    # ------------------------------------------------------ program cache
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _geometry_signature(self) -> str:
        """Model/engine-geometry signature for the compile-cache
        fingerprint: an executable is only reusable when every array
        SHAPE it was lowered against matches, so the weight-state
        shapes/dtypes and the KV-pool geometry define validity (weight
        VALUES are call-time arguments, not baked in)."""
        import hashlib
        state = ";".join(f"{k}:{tuple(a.shape)}:{a.dtype}"
                         for k, a in sorted(self._state.items()))
        sig = (f"{type(self.model).__name__}|{state}|"
               f"pages={self.num_pages}x{self.page_size}|"
               f"layers={self.num_layers}")
        return hashlib.sha256(sig.encode()).hexdigest()[:16]

    @property
    def compile_cache(self):
        """The persistent CompileCache (None when not configured)."""
        return self.programs.disk

    def _sync_compile_cache_counters(self):
        """Mirror the CompileCache counters into the auto-exposed
        metrics counters (the Prometheus drift-test registry): the
        keys exist on every engine, zeroed when the cache is off."""
        cc = self.programs.disk
        if cc is not None:
            for k in ("hits", "misses", "rejects"):
                self.metrics.counters[f"compile_cache_{k}"] = \
                    cc.counters[k]

    def save_compile_cache(self) -> int:
        """Persist every launched program to the compile cache (no-op
        without one). Re-lowers AOT per new entry — a drain/shutdown-
        time cost; returns entries written. Workers call this on
        drain/SIGTERM so their successor reaches first-token without
        the compile storm (ISSUE 14)."""
        cc = self.programs.disk
        if cc is None:
            return 0
        written = cc.save_all(self.programs)
        self._sync_compile_cache_counters()
        return written

    def _get_program(self, key, builder):
        prog = self.programs.get(key, builder)
        self._sync_compile_cache_counters()
        return prog

    @property
    def num_compiled_programs(self) -> int:
        """Total compiled programs (all families); per-family counts via
        `program_counts()` (ISSUE 8)."""
        return self.programs.num_programs

    def program_counts(self) -> Dict[str, int]:
        """{family: programs compiled} for the chunk/decode/verify
        families through the unified ProgramCache."""
        return self.programs.counts()

    def comm_table(self) -> Dict[tuple, Optional[dict]]:
        """Per-program collective-traffic accounting (ISSUE 12), axis-
        attributed over THIS engine's mesh — the TP row-parallel psum
        on 'model' shows up on the decode rows. Compile-time-only cost,
        like cost_table()."""
        return self.programs.comm_table(mesh=self.mesh)

    def max_program_count(self, family: Optional[str] = None) -> int:
        """The bucket-grid bound the recompile counter can never exceed
        — one family's grid, or (default) the sum over all families.
        With a proposer the ("verify", B, K, P) grid joins it: K is a
        program-cache key axis exactly like B and P, so speculative
        decoding multiplies the decode-side bound by len(spec_buckets)
        instead of compiling per draft length (SERVING.md documents the
        bound next to the PR-1 bucket-grid note). The mesh shape also
        rides every key, but an engine owns ONE mesh, so its bound is
        the grid for that single mesh shape."""
        return self.programs.max_count(family)

    # --------------------------------------------- paged-cache plumbing
    @staticmethod
    def _paged_views(kcs, vcs, kss, vss):
        """Per-layer cache tuples for the model's forward_paged_* —
        (k, v) for full-width KV, (k, v, k_scale, v_scale) for int8
        (the model branches on tuple arity, ISSUE 6)."""
        if kss:
            return [(Tensor(kcs[l]), Tensor(vcs[l]),
                     Tensor(kss[l]), Tensor(vss[l]))
                    for l in range(len(kcs))]
        return [(Tensor(kcs[l]), Tensor(vcs[l]))
                for l in range(len(kcs))]

    @staticmethod
    def _split_views(caches):
        """Inverse of _paged_views: four flat array lists (scale lists
        empty for full-width KV) — the uniform program return shape."""
        kcs = [c[0]._data for c in caches]
        vcs = [c[1]._data for c in caches]
        if caches and len(caches[0]) == 4:
            return (kcs, vcs, [c[2]._data for c in caches],
                    [c[3]._data for c in caches])
        return kcs, vcs, [], []

    def _store_caches(self, kcs, vcs, kss, vss):
        self._k_caches, self._v_caches = kcs, vcs
        self._k_scales, self._v_scales = kss, vss

    # ----------------------------------------------------- prefill chunks
    def _build_chunk(self, S: int, P: int):
        """One padded prompt CHUNK -> paged cache + sampled token (the
        token is only consumed when the chunk is the prompt's last)."""
        # tpu-lint: cache-key-ok (per-engine cache; disk tier keys geometry)
        model = self.model
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        views, split = self._paged_views, self._split_views
        lora_open = self._lora_trace_scope

        def program(state, kcs, vcs, kss, vss, ids, cache_len, live, bt,
                    key, *largs):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = views(kcs, vcs, kss, vss)
            with lora_open(largs):
                logits, caches = functional_call(
                    model, st, Tensor(ids), paged, Tensor(bt),
                    Tensor(cache_len), Tensor(live),
                    method="forward_paged_prefill")
            last = logits._data[0, 0]   # head ran at the chunk end only
            # in-graph NaN detection (the jit counterpart of the eager
            # dispatch NaN hook): NaN/Inf anywhere in the network flows
            # into the chunk-end logits, so one reduction covers the step
            ok = jnp.all(jnp.isfinite(last))
            tok = _sample_arr(last[None], key, temperature, top_k, top_p)[0]
            return (tok, ok) + split(caches)

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    def _run_chunk(self, chunk):
        from .. import profiler
        req = chunk.request
        ids = req.resume_ids[chunk.start:chunk.start + chunk.length]
        S = _bucket_for(chunk.length, self.prefill_buckets)
        P = _bucket_for(
            self.allocator.pages_needed(chunk.start + chunk.length),
            self.pages_buckets)
        prog = self._get_program(("chunk", S, P) + self._qkey,
                                 lambda: self._build_chunk(S, P))
        bt = np.full((P,), PAD_PAGE, np.int32)
        npages = min(len(req.seq.pages), P)
        bt[:npages] = req.seq.pages[:npages]
        padded = np.zeros((1, S), np.int32)
        padded[0, :chunk.length] = ids
        # the RNG key is drawn ONCE, before the supervised launch, so a
        # transient-failure retry re-runs the identical program (bit-
        # identical token) instead of burning a new key per attempt
        key = self._next_key() if chunk.is_last else self._null_key
        largs = self._lora_launch_args([req], 1)

        def launch():
            faults.fire(FAULT_CHUNK)
            with profiler.RecordEvent("serving.prefill_chunk"), \
                    poison_scope(f"serving.prefill_chunk[req="
                                 f"{req.request_id}]"), no_grad(), \
                    self._trace_scope():
                return prog(
                    self._state, self._k_caches, self._v_caches,
                    self._k_scales, self._v_scales,
                    jnp.asarray(padded), jnp.int32(chunk.start),
                    jnp.int32(chunk.length), jnp.asarray(bt), key,
                    *largs)

        self._cur_rids = (req.request_id,)
        self._step_ev["programs"].append(f"chunk:S{S}:P{P}")
        t_tr = self.tracer.now_ns() if self.tracer is not None else 0
        tok, ok, *caches = self.supervisor.run(launch,
                                               label="prefill_chunk")
        self._tr_launch((req.request_id,), "prefill_chunk", t_tr,
                        start=chunk.start, length=chunk.length,
                        bucket=[S, P], last=chunk.is_last)
        self._store_caches(*caches)
        if faults.fire(FAULT_NAN) is not None:
            ok = False
        self.metrics.on_prefill(chunk.length)
        # the chunk wrote its own tokens' K/V and its attention gathered
        # the whole live prefix (cached tokens + this chunk) per layer
        self.metrics.on_kv_bytes(
            written=chunk.length * self.kv_bytes_per_token,
            read=(chunk.start + chunk.length) * self.kv_bytes_per_token)
        return tok, bool(ok)

    # ----------------------------------------------------------- decode
    def _build_decode(self, B: int, P: int):
        """One batched token step over the paged caches."""
        # tpu-lint: cache-key-ok (per-engine cache; disk tier keys geometry)
        model = self.model
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        views, split = self._paged_views, self._split_views
        lora_open = self._lora_trace_scope

        def program(state, kcs, vcs, kss, vss, ids, bt, sl, key, *largs):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = views(kcs, vcs, kss, vss)
            with lora_open(largs):
                logits, caches = functional_call(
                    model, st, Tensor(ids), paged, Tensor(bt), Tensor(sl),
                    method="forward_paged_decode")
            rows = logits._data[:, 0, :]
            # per-row finiteness: rows are independent (SERVING.md), so a
            # poisoned request flags ONLY its own row — the quarantine
            # granularity ("fail one request, not the engine")
            ok = jnp.all(jnp.isfinite(rows), axis=-1)
            toks = _sample_arr(rows, key, temperature, top_k, top_p)
            return (toks, ok) + split(caches)

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    def _run_decode(self, reqs: List[Request]):
        from .. import profiler
        B = _bucket_for(len(reqs), self.batch_buckets)
        max_pages = max(len(r.seq.pages) for r in reqs)
        P = _bucket_for(max_pages, self.pages_buckets)
        prog = self._get_program(("decode", B, P) + self._qkey,
                                 lambda: self._build_decode(B, P))
        ids = np.zeros((B, 1), np.int32)
        sl = np.zeros((B,), np.int32)
        seqs = [r.seq for r in reqs]
        bt = np.full((B, P), PAD_PAGE, np.int32)
        bt[:len(reqs)] = self.allocator.block_table(seqs, P)
        for i, r in enumerate(reqs):
            ids[i, 0] = r.output_ids[-1]
            sl[i] = r.seq.num_tokens
        key = self._next_key()    # drawn once: retries re-run identically
        rids = [r.request_id for r in reqs]
        largs = self._lora_launch_args(reqs, B)
        if self.lora is not None:
            self.metrics.on_adapter_mix(
                len({r.adapter for r in reqs if r.adapter is not None}))

        def launch():
            faults.fire(FAULT_DECODE)
            with profiler.RecordEvent("serving.decode_step"), \
                    poison_scope(f"serving.decode_step[reqs={rids}]"), \
                    no_grad(), self._trace_scope():
                return prog(
                    self._state, self._k_caches, self._v_caches,
                    self._k_scales, self._v_scales,
                    jnp.asarray(ids), jnp.asarray(bt), jnp.asarray(sl),
                    key, *largs)

        self._cur_rids = tuple(rids)
        self._step_ev["programs"].append(f"decode:B{B}:P{P}")
        self._step_ev["decode_k"] = 1
        t_tr = self.tracer.now_ns() if self.tracer is not None else 0
        t0 = _perf_counter()
        toks, oks, *caches = self.supervisor.run(launch,
                                                 label="decode_step")
        toks = np.asarray(toks)        # host fetch = the honest sync
        self._last_launch_s = _perf_counter() - t0
        self._tr_launch(rids, "decode_step", t_tr, batch=len(reqs),
                        bucket=[B, P], k=1)
        self._store_caches(*caches)
        # bytes-moved accounting: this step wrote one token per live row
        # and the attention kernel read every live token's K/V
        self.metrics.on_kv_bytes(
            written=len(reqs) * self.kv_bytes_per_token,
            read=sum(r.seq.num_tokens for r in reqs)
            * self.kv_bytes_per_token)
        oks = np.asarray(oks)[:len(reqs)].copy()
        poison = faults.fire(FAULT_NAN)
        if poison is not None:
            for i in self._poison_rows(poison, reqs):
                oks[i] = False
        for r in reqs:
            # this step wrote the K/V of each row's input token
            r.num_computed = r.seq.num_tokens
        self.metrics.on_decode(len(reqs))
        return toks, oks

    @staticmethod
    def _poison_rows(poison, reqs) -> List[int]:
        """Normalize a nan_logits fault payload into row indices:
        callable(reqs) -> rows, True/'all' -> every row, int or list of
        ints -> those rows (out-of-range ignored)."""
        if callable(poison):
            rows = poison(reqs)
        elif poison is True or poison == "all":
            rows = range(len(reqs))
        elif isinstance(poison, int):
            rows = [poison]
        else:
            rows = poison
        return [int(i) for i in rows if 0 <= int(i) < len(reqs)]

    # --------------------------------------- multi-step decode (ISSUE 13)
    def _build_multi_decode(self, B: int, K: int, P: int):
        """K decode iterations in ONE compiled launch: a device-side
        scan over the decode body with in-graph sampling (per-step keys
        folded from the one pre-drawn launch key), per-step paged cache
        writes through the loop carry, and per-row freeze masks
        (EOS / per-row step cap / non-finite logits). The host fetches
        only (tokens (B, K), emitted counts, finiteness flags) — one
        relay round trip buys up to K tokens per row."""
        # tpu-lint: cache-key-ok (per-engine cache; disk tier keys geometry)
        model = self.model
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        views, split = self._paged_views, self._split_views
        lora_open = self._lora_trace_scope

        def program(state, kcs, vcs, kss, vss, ids, bt, sl, caps, eos,
                    key, *largs):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = views(kcs, vcs, kss, vss)
            # the scope spans the whole scan trace: the gathered slot
            # stacks become loop constants, so the paged gather runs
            # once per LAUNCH, not once per decode step
            with lora_open(largs):
                toks, n_emit, ok, caches = functional_call(
                    model, st, Tensor(ids), paged, Tensor(bt), Tensor(sl),
                    Tensor(caps), Tensor(eos), key,
                    method="forward_paged_decode_multi", k_steps=K,
                    temperature=temperature, top_k=top_k, top_p=top_p)
            return (toks._data, n_emit._data, ok._data) + split(caches)

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    def _run_multi_decode(self, reqs: List[Request], caps: List[int],
                          K: int):
        """One supervised ("multi_decode", B, K, P) launch. `reqs[i]`'s
        sequence is already extended by caps[i] - 1 slots; returns
        (toks (B, K), n_emit (B,), oks (B,), launch seconds)."""
        from .. import profiler
        B = _bucket_for(len(reqs), self.batch_buckets)
        max_pages = max(len(r.seq.pages) for r in reqs)
        P = _bucket_for(max_pages, self.pages_buckets)
        prog = self._get_program(("multi_decode", B, K, P) + self._qkey,
                                 lambda: self._build_multi_decode(B, K, P))
        ids = np.zeros((B,), np.int32)
        sl = np.zeros((B,), np.int32)
        cp = np.zeros((B,), np.int32)
        eos = np.full((B,), -1, np.int32)
        bt = np.full((B, P), PAD_PAGE, np.int32)
        seqs = [r.seq for r in reqs]
        bt[:len(reqs)] = self.allocator.block_table(seqs, P)
        for i, (r, c) in enumerate(zip(reqs, caps)):
            ids[i] = r.output_ids[-1]
            # seq_lens counts through the FIRST input token (the
            # forward_paged convention); the extension slots grew
            # num_tokens past it, so subtract them back out
            sl[i] = r.seq.num_tokens - (c - 1)
            cp[i] = c
            if r.eos_token_id is not None:
                eos[i] = r.eos_token_id
        key = self._next_key()    # drawn once: retries re-run identically
        rids = [r.request_id for r in reqs]
        largs = self._lora_launch_args(reqs, B)
        if self.lora is not None:
            self.metrics.on_adapter_mix(
                len({r.adapter for r in reqs if r.adapter is not None}))

        def launch():
            faults.fire(FAULT_MULTI)
            with profiler.RecordEvent("serving.multi_decode_step"), \
                    poison_scope(f"serving.multi_decode_step[reqs="
                                 f"{rids}]"), no_grad(), \
                    self._trace_scope():
                return prog(
                    self._state, self._k_caches, self._v_caches,
                    self._k_scales, self._v_scales,
                    jnp.asarray(ids), jnp.asarray(bt), jnp.asarray(sl),
                    jnp.asarray(cp), jnp.asarray(eos), key, *largs)

        self._cur_rids = tuple(rids)
        self._step_ev["programs"].append(f"multi_decode:B{B}:K{K}:P{P}")
        self._step_ev["decode_k"] = K
        t_tr = self.tracer.now_ns() if self.tracer is not None else 0
        t0 = _perf_counter()
        toks, n_emit, oks, *caches = self.supervisor.run(
            launch, label="multi_decode_step")
        # host fetch = the only honest sync over the relay: convert
        # BEFORE stamping the launch time so TPOT covers device work
        toks = np.asarray(toks)
        n_emit = np.asarray(n_emit).astype(int)
        oks = np.asarray(oks)[:len(reqs)].copy()
        dt = _perf_counter() - t0
        self._tr_launch(rids, "multi_decode_step", t_tr, batch=len(reqs),
                        bucket=[B, K, P], k=K)
        self._store_caches(*caches)
        # bytes-moved accounting: every live row writes one token's K/V
        # per step (frozen steps idempotently rewrite the last token),
        # and each step's attention reads the row's then-current prefix
        # (frozen rows re-read at their frozen length)
        base_lens = sl[:len(reqs)].astype(int)
        reads = sum(int(b0) * K + sum(min(j, int(e)) for j in range(K))
                    for b0, e in zip(base_lens, n_emit[:len(reqs)]))
        self.metrics.on_kv_bytes(
            written=len(reqs) * K * self.kv_bytes_per_token,
            read=reads * self.kv_bytes_per_token)
        for r in reqs:
            r.num_computed = r.seq.num_tokens
        poison = faults.fire(FAULT_NAN)
        if poison is not None:
            for i in self._poison_rows(poison, reqs):
                oks[i] = False
        return toks, n_emit, oks, dt

    def _multi_decode_step(self, decodes: List[Request], emitted):
        """The multi-step replacement for the plain decode launch:
        extend each sequence by up to K-1 slots -> ONE scan launch ->
        emit each row's tokens up to its in-graph freeze point -> roll
        unused slots back.

        Failure semantics mirror the decode step: transients retried by
        the supervisor (writes are idempotent, the RNG key pre-drawn);
        a row whose per-launch finiteness flag is down is quarantined
        alone and delivers NO token from the poisoned launch (per-LAUNCH
        quarantine granularity — SERVING.md); unattributed poison rolls
        the extension slots back and isolates via solo PLAIN decode
        launches; anything else drains to a snapshot. Abort/TTL are
        honored at the next K-boundary with this launch's tokens
        delivered."""
        caps = []
        for req in decodes:
            want = min(self.decode_steps, req.remaining_new_tokens())
            granted, copies = self._extend_slots(req, want - 1)
            if granted < want - 1:
                self.metrics.counters["multi_decode_slot_shortfall"] += \
                    (want - 1) - granted
            if copies:
                self._apply_copies(copies)
            caps.append(1 + granted)
        K = _bucket_for(max(caps), self.multi_buckets)
        isolated = False
        dt = None
        try:
            toks, n_emit, oks, dt = self._run_multi_decode(
                decodes, caps, K)
        except Exception as exc:   # noqa: BLE001
            if classify_failure(exc) != POISON:
                self._fail(exc)
            # unattributed poison: drop the extension slots (their K/V
            # is suspect) and isolate with solo plain-decode launches
            for req, cap in zip(decodes, caps):
                if cap > 1:
                    self.allocator.truncate_sequence(
                        req.seq, req.seq.num_tokens - (cap - 1))
            toks1, oks = self._isolate_poisoned(decodes)
            toks = np.full((len(decodes), 1), -1, np.int64)
            toks[:, 0] = toks1
            n_emit = np.ones((len(decodes),), int)
            caps = [1] * len(decodes)
            isolated = True
        total_emitted = 0
        for i, req in enumerate(decodes):
            base = req.seq.num_tokens - (caps[i] - 1)  # through input tok
            if not oks[i]:
                # per-launch quarantine: pages (extension slots
                # included) freed WITHOUT donation, no token delivered
                self._quarantine(req)
                continue
            e = int(n_emit[i])
            reason = None
            n_done = 0
            for j in range(e):
                reason = self._emit(req, int(toks[i, j]), emitted)
                n_done += 1
                if reason is not None:
                    break
            # valid K/V: the input token + the emitted tokens actually
            # CONSUMED as later in-graph inputs (n_done - 1 of them);
            # unused extension slots roll back so donation/resume never
            # sees past-freeze garbage
            valid = base + max(n_done, 1) - 1
            if req.seq.num_tokens > valid:
                self.allocator.truncate_sequence(req.seq, valid)
            req.num_computed = valid
            total_emitted += n_done
            if reason is not None:
                self.scheduler.finish(req, reason)
                self._on_finished(req)
        if not isolated:
            self.metrics.on_decode(total_emitted)
            self.metrics.on_decode_launch(K, len(decodes), total_emitted,
                                          dt)
        else:
            # the isolation path's solo launches counted decode_tokens
            # inside _run_decode; record their row count too (one row
            # per solo launch, k=1, no timing) or the
            # tokens-per-launch ratio would keep a numerator with no
            # denominator and read ABOVE its true value after any
            # degraded event
            self.metrics.on_decode_launch(1, len(decodes), 0, None)

    # ------------------------------------------- speculative verify (ISSUE 5)
    def _build_verify(self, B: int, K: int, P: int):
        """One speculative VERIFY launch: scores each row's
        [last emitted token, draft_1..draft_K] in one pass over the
        paged caches and resolves acceptance IN-GRAPH, so the host
        fetches only (tokens, accepted counts, finiteness flags).

        Acceptance implements rejection sampling for a DETERMINISTIC
        (one-hot) proposal — both shipped proposers draft greedily:
        * temperature == 0: longest prefix with argmax(prev logits) ==
          draft, then the argmax correction/bonus token. Emitted tokens
          are exactly the argmaxes plain decode would emit, which is
          the greedy bit-identity contract.
        * temperature > 0: draft d at position j accepts iff
          u_j < p_j(d) (p = the SAME filtered/tempered distribution
          `_sample_arr` uses); a rejected position samples the
          renormalized remainder of p with d removed — exact residual
          for a one-hot proposal, so the output distribution equals
          plain sampled decode's. All randomness derives from the one
          pre-drawn key, so StepSupervisor retries stay bit-identical.
        """
        S = K + 1
        # tpu-lint: cache-key-ok (per-engine cache; disk tier keys geometry)
        model = self.model
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        views, split = self._paged_views, self._split_views

        def program(state, kcs, vcs, kss, vss, ids, bt, sl, dl, key):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = views(kcs, vcs, kss, vss)
            logits, caches = functional_call(
                model, st, Tensor(ids), paged, Tensor(bt), Tensor(sl),
                Tensor(dl), method="forward_paged_verify")
            lg = logits._data                            # (B, S, V)
            jpos = jnp.arange(S, dtype=jnp.int32)[None, :]
            live_q = jpos <= dl[:, None]                 # (B, S)
            # per-row finiteness over LIVE positions only (padding rows
            # run on clamped positions; only real work may quarantine)
            fin = jnp.all(jnp.isfinite(lg), axis=-1)
            ok = jnp.all(jnp.where(live_q, fin, True), axis=-1)
            drafts = ids[:, 1:]                          # (B, K)
            # position j's logits score draft j+1: live iff j < dl
            has_draft = jpos[:, :K] < dl[:, None]
            idsn = jnp.concatenate(
                [drafts, jnp.zeros((B, 1), ids.dtype)], axis=1)  # (B, S)
            if temperature <= 0.0:
                pred = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                acc = jnp.logical_and(pred[:, :K] == drafts, has_draft)
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32),
                                            axis=1), axis=1)
                toks = jnp.where(jpos < n_acc[:, None], idsn, pred)
            else:
                p = jax.nn.softmax(
                    _filter_logits(lg, temperature, top_k, top_p),
                    axis=-1)
                k_u, k_r = jax.random.split(key)
                u = jax.random.uniform(k_u, (B, K))
                p_draft = jnp.take_along_axis(
                    p[:, :K], drafts[..., None].astype(jnp.int32),
                    axis=-1)[..., 0]
                acc = jnp.logical_and(u < p_draft, has_draft)
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32),
                                            axis=1), axis=1)
                # residual at a draft position = p with the draft token
                # zeroed + renormalized (the rejected position has
                # p(d) < u <= 1, so the remainder has positive mass);
                # the bonus position (j == dl) samples p itself
                has_draft_s = jpos < dl[:, None]         # (B, S)
                onehot = jax.nn.one_hot(idsn.astype(jnp.int32),
                                        p.shape[-1], dtype=p.dtype)
                res = p * (1.0 - jnp.where(has_draft_s[..., None],
                                           onehot, 0.0))
                res = res / jnp.maximum(
                    jnp.sum(res, axis=-1, keepdims=True), 1e-30)
                sampled = jax.random.categorical(
                    k_r, jnp.log(res + 1e-30), axis=-1).astype(jnp.int32)
                toks = jnp.where(jpos < n_acc[:, None], idsn, sampled)
            return (toks, n_acc, ok) + split(caches)

        # tpu-lint: cache-key-ok (donation is backend-constant per process)
        return jax.jit(program, donate_argnums=self._donate)

    def _extend_slots(self, req: Request, want: int):
        """Grow the request's sequence by up to `want` token slots (the
        scheduler already reserved this launch's input-token slot).
        On pool exhaustion the reclamation ladder stops at its FIRST
        rung — radix LRU eviction of zero-active-ref cached prefixes
        (otherwise a long-lived server whose pool has filled with
        donated prefixes, the normal steady state, would drop every
        extra slot and silently lose the multi-token win) — but NEVER
        preempts: the extra slots are advisory (draft tokens / extra
        decode steps), and evicting live work to make room for them
        would invert the priority order. Degrades, never fails:
        `append_token` is atomic, so a dry pool just grants fewer
        slots — zero means the launch degenerates to a single step.
        Returns (granted, CoW copies due)."""
        base = req.seq.num_tokens
        copies, granted = [], 0
        for _ in range(want):
            try:
                copies.extend(self.allocator.append_token(req.seq))
            except BlocksExhausted:
                if not self.scheduler._reclaim(1):
                    break
                try:
                    copies.extend(self.allocator.append_token(req.seq))
                except BlocksExhausted:
                    break
            granted += 1
        assert req.seq.num_tokens == base + granted
        return granted, copies

    def _extend_for_drafts(self, req: Request, draft: List[int]):
        """Spec-decode slot extension: grow by up to len(draft) slots
        via `_extend_slots`, shortening the draft to what the pool
        granted. Returns (granted draft list, CoW copies due)."""
        granted, copies = self._extend_slots(req, len(draft))
        if granted < len(draft):
            self.metrics.on_spec_draft_oom(len(draft) - granted)
        del draft[granted:]
        return draft, copies

    def _run_verify(self, reqs: List[Request], drafts: List[List[int]]):
        """One supervised ("verify", B, K, P) launch. `reqs[i]`'s
        sequence is already extended by len(drafts[i]); returns
        (toks (B, K+1), n_acc (B,), oks (B,))."""
        from .. import profiler
        B = _bucket_for(len(reqs), self.batch_buckets)
        K = _bucket_for(max((len(d) for d in drafts), default=0) or 1,
                        self.spec_buckets)
        max_pages = max(len(r.seq.pages) for r in reqs)
        P = _bucket_for(max_pages, self.pages_buckets)
        prog = self._get_program(("verify", B, K, P) + self._qkey,
                                 lambda: self._build_verify(B, K, P))
        S = K + 1
        ids = np.zeros((B, S), np.int32)
        sl = np.zeros((B,), np.int32)
        dl = np.zeros((B,), np.int32)
        bt = np.full((B, P), PAD_PAGE, np.int32)
        seqs = [r.seq for r in reqs]
        bt[:len(reqs)] = self.allocator.block_table(seqs, P)
        for i, (r, d) in enumerate(zip(reqs, drafts)):
            ids[i, 0] = r.output_ids[-1]
            ids[i, 1:1 + len(d)] = d
            dl[i] = len(d)
            # seq_lens counts through the FIRST input token (the
            # forward_paged convention); the drafts extended num_tokens
            # past it, so subtract them back out
            sl[i] = r.seq.num_tokens - len(d)
        key = self._next_key()    # drawn once: retries re-run identically
        rids = [r.request_id for r in reqs]

        def launch():
            faults.fire(FAULT_VERIFY)
            with profiler.RecordEvent("serving.verify_step"), \
                    poison_scope(f"serving.verify_step[reqs={rids}]"), \
                    no_grad(), self._trace_scope():
                return prog(
                    self._state, self._k_caches, self._v_caches,
                    self._k_scales, self._v_scales,
                    jnp.asarray(ids), jnp.asarray(bt), jnp.asarray(sl),
                    jnp.asarray(dl), key)

        self._cur_rids = tuple(rids)
        self._step_ev["programs"].append(f"verify:B{B}:K{K}:P{P}")
        # tokens-per-launch context for the step record: a verify
        # launch can emit up to K drafts + 1 correction/bonus per row
        self._step_ev["decode_k"] = K + 1
        t_tr = self.tracer.now_ns() if self.tracer is not None else 0
        toks, n_acc, oks, *caches = self.supervisor.run(
            launch, label="verify_step")
        if self.tracer is not None:
            t1 = self.tracer.now_ns()
            for rid, d in zip(rids, drafts):
                self.tracer.span(rid, "verify_step", t_tr, t1,
                                 engine=self.metrics.name,
                                 batch=len(reqs), drafted=len(d),
                                 bucket=[B, K, P])
        self._store_caches(*caches)
        self.metrics.on_kv_bytes(
            written=int(sum(1 + len(d) for d in drafts))
            * self.kv_bytes_per_token,
            read=sum(r.seq.num_tokens for r in reqs)
            * self.kv_bytes_per_token)
        oks = np.asarray(oks)[:len(reqs)].copy()
        poison = faults.fire(FAULT_NAN)
        if poison is not None:
            for i in self._poison_rows(poison, reqs):
                oks[i] = False
        return (np.asarray(toks), np.asarray(n_acc).astype(int), oks)

    def _spec_decode_step(self, decodes: List[Request], emitted):
        """The speculative replacement for the plain decode launch:
        propose -> extend KV -> ONE verify launch -> emit the accepted
        prefix + correction/bonus -> roll rejected drafts' pages back.

        Failure semantics mirror the decode step: transients retried by
        the supervisor (the verify write is idempotent and the RNG key
        pre-drawn); per-row poison quarantines alone; unattributed
        poison rolls every draft back and isolates via solo PLAIN
        decode launches (the degraded path already documented for
        decode); anything else drains to a snapshot."""
        # drafts are advisory and capped so the emitted tokens can never
        # overshoot max_new_tokens: a request with r remaining tokens
        # can use at most r - 1 accepted drafts (+1 correction/bonus)
        proposals = self.proposer.propose(decodes, self.spec_k)
        storm = faults.fire(FAULT_DRAFT)
        if storm is not None:
            proposals = (storm(decodes, self.spec_k) if callable(storm)
                         else [[(i * 7 + j * 13 + 1) %
                                max(2, self.cfg.vocab_size)
                                for j in range(self.spec_k)]
                               for i in range(len(decodes))])
        drafts = []
        for req, prop in zip(decodes, proposals):
            cap = max(0, min(self.spec_k, req.remaining_new_tokens() - 1))
            d = [int(t) for t in list(prop)[:cap]]
            d, copies = self._extend_for_drafts(req, d)
            if copies:
                self._apply_copies(copies)
            drafts.append(d)

        isolated = False
        try:
            toks, n_accs, oks = self._run_verify(decodes, drafts)
        except Exception as exc:   # noqa: BLE001
            if classify_failure(exc) != POISON:
                self._fail(exc)
            # unattributed poison: drop every draft (their K/V is
            # suspect) and isolate with solo plain-decode launches
            for req, d in zip(decodes, drafts):
                if d:
                    self.allocator.truncate_sequence(
                        req.seq, req.seq.num_tokens - len(d))
            # the rolled-back drafts are real rollback work even though
            # no verify step completed — count them without minting a
            # phantom spec step
            self.metrics.counters["spec_rollback_tokens"] += sum(
                len(d) for d in drafts)
            toks1, oks = self._isolate_poisoned(decodes)
            toks = np.zeros((len(decodes), 2), np.int64)
            toks[:, 0] = toks1
            n_accs = np.zeros((len(decodes),), int)
            drafts = [[] for _ in decodes]
            isolated = True   # solo launches counted their own tokens

        total_drafted = total_accepted = total_emitted = total_rb = 0
        rows = 0
        for i, req in enumerate(decodes):
            d = drafts[i]
            base = req.seq.num_tokens - len(d)   # tokens through input
            if not oks[i]:
                # quarantine frees the whole sequence (no donation) —
                # rejected-draft pages go with it
                self._quarantine(req)
                continue
            n_emit = 0
            reason = None
            for j in range(int(n_accs[i]) + 1):
                reason = self._emit(req, int(toks[i, j]), emitted)
                n_emit += 1
                if reason is not None:
                    break
            # valid K/V: the input token + the accepted drafts actually
            # CONSUMED (n_emit - 1 of them); everything past it rolls
            # back so donation/resume never sees speculative garbage
            valid = base + n_emit - 1
            rolled = req.seq.num_tokens - valid
            if rolled:
                self.allocator.truncate_sequence(req.seq, valid)
            req.num_computed = valid
            total_drafted += len(d)
            total_accepted += n_emit - 1
            total_emitted += n_emit
            total_rb += rolled
            rows += 1
            if reason is not None:
                self.scheduler.finish(req, reason)
                self._on_finished(req)
        # decode_tokens counts tokens EMITTED by decode-side launches
        # (1/request for plain decode) so tokens/s stays honest. The
        # isolation path counted its own solo launches and verified
        # nothing — recording a spec step for it would drag
        # spec_tokens_per_step below its true value.
        if not isolated:
            self.metrics.on_decode(total_emitted)
            self.metrics.on_spec_step(total_drafted, total_accepted,
                                      total_emitted, total_rb, rows)

    # ---------------------------------------------------- CoW page copies
    def _apply_copies(self, copies):
        """Device-side CoW: copy a page's rows to a fresh page. For
        int8 KV the per-slot scale rows are part of the page's identity
        and copy WITH it — a fork that only copied values would
        dequantize the new page with the old (soon divergent) scales."""
        for src, dst in copies:
            for l in range(self.num_layers):
                self._k_caches[l] = self._k_caches[l].at[dst].set(
                    self._k_caches[l][src])
                self._v_caches[l] = self._v_caches[l].at[dst].set(
                    self._v_caches[l][src])
            for l in range(len(self._k_scales)):
                self._k_scales[l] = self._k_scales[l].at[dst].set(
                    self._k_scales[l][src])
                self._v_scales[l] = self._v_scales[l].at[dst].set(
                    self._v_scales[l][src])

    # ------------------------------------- tiered KV page I/O (ISSUE 17)
    def _gather_page_payload(self, pid: int) -> bytes:
        """One device page's bytes as an encoded payload: k row, v row
        per layer, then the int8 scale rows when the cache is
        quantized. A real device->host fetch per array (np.asarray is
        the only honest sync over the relay). The byte round trip is
        exact — np.asarray and .at[].set move raw rows, so a promoted
        page is bit-identical to the page that was demoted."""
        arrays = []
        for l in range(self.num_layers):
            arrays.append(np.asarray(self._k_caches[l][pid]))
            arrays.append(np.asarray(self._v_caches[l][pid]))
        for l in range(len(self._k_scales)):
            arrays.append(np.asarray(self._k_scales[l][pid]))
            arrays.append(np.asarray(self._v_scales[l][pid]))
        return encode_page_payload(arrays)

    def _scatter_page_payload(self, pid: int, arrays) -> None:
        """Inverse of `_gather_page_payload` onto device page `pid`:
        enqueues the per-layer `.at[pid].set(...)` writes and returns
        WITHOUT a host sync — the copies overlap whatever launch comes
        next, and the device stream orders them before any kernel that
        reads the page. Raises HostPageCorrupt on an array-count
        mismatch (a decoded payload from a different engine geometry
        must never partially land)."""
        expect = 2 * (self.num_layers + len(self._k_scales))
        if len(arrays) != expect:
            raise HostPageCorrupt(
                f"page payload has {len(arrays)} arrays; this engine "
                f"needs {expect}")
        it = iter(arrays)
        for l in range(self.num_layers):
            self._k_caches[l] = self._k_caches[l].at[pid].set(
                jnp.asarray(next(it)))
            self._v_caches[l] = self._v_caches[l].at[pid].set(
                jnp.asarray(next(it)))
        for l in range(len(self._k_scales)):
            self._k_scales[l] = self._k_scales[l].at[pid].set(
                jnp.asarray(next(it)))
            self._v_scales[l] = self._v_scales[l].at[pid].set(
                jnp.asarray(next(it)))

    def _spill_gauges(self) -> dict:
        """update_gauges kwargs for the radix eviction rungs and the
        host spill tier — empty fields stay None-untouched, so a
        cache-off or spill-off engine never zeroes counters it does
        not own. Called at BOTH gauge sites (step and vacate)."""
        out = {}
        if self.radix is not None:
            out.update(
                radix_evict_demoted=self.radix.num_evict_demoted,
                radix_evict_dropped=self.radix.num_evict_dropped)
        if self.host_store is not None:
            out.update(
                host_pages_used=self.host_store.num_used,
                host_occupancy=self.host_store.occupancy(),
                kv_pages_demoted=self.radix.num_demoted_pages,
                kv_pages_promoted=self.radix.num_promoted_pages,
                host_prefix_hits=self.radix.num_host_hits,
                host_pages_dropped=self.radix.num_host_dropped_pages)
        return out

    # ------------------------------------------------------------- step
    def _emit(self, req: Request, tok: int, emitted):
        """Record one generated token + run the finish checks."""
        first = req.num_generated == 0
        req.output_ids.append(tok)
        if first:
            self.metrics.on_first_token(req.request_id)
            self._tr_mark(req.request_id, "first_token")
        emitted.append((req.request_id, tok))
        if req.eos_token_id is not None and tok == req.eos_token_id:
            return "stop"
        if req.remaining_new_tokens() <= 0:
            return "length"
        return None

    # ------------------------------------------- boundary cancellations
    def _cancel_boundary(self):
        """Iteration-boundary cancellation sweep: apply any injected
        clock skew (deadline-storm fault), then cancel aborted and
        past-deadline requests in ANY state. Valid KV is donated."""
        skew = faults.fire(FAULT_STORM)
        if skew is not None:
            self._clock_skew += float(skew)
        now = self._now()
        for req in list(self.requests.values()):
            if req.state is RequestState.FINISHED:
                continue
            if req.aborted:
                if self.scheduler.cancel(req, "abort"):
                    self.metrics.on_abort(req.request_id)
                    self._tr_finish(req.request_id, "abort")
                    self._retain(req)
            elif req.deadline is not None and now >= req.deadline:
                if self.scheduler.cancel(req, "expired"):
                    self.metrics.on_expire(req.request_id)
                    self._tr_finish(req.request_id, "expired")
                    self._retain(req)

    def _quarantine(self, req: Request):
        """Fail ONE poisoned request, not the engine: no token is
        emitted, its pages are freed WITHOUT donation (they may hold
        NaN K/V — the radix tree must never serve them)."""
        if self.scheduler.cancel(req, "quarantined", donate=False):
            self.metrics.on_quarantine(req.request_id)
            self._tr_mark(req.request_id, "quarantined")
            self._tr_finish(req.request_id, "quarantined")
            self._retain(req)

    def _fail(self, exc: BaseException):
        """Unrecoverable: drain to a serializable snapshot and raise
        EngineFailure. The engine refuses further work afterwards."""
        self.metrics.on_engine_failure()
        # stamp the FAILING (partial) step into the flight recorder
        # before the snapshot captures the ring — the postmortem's
        # last record is the step that died, not merely the one before
        self.recorder.record({
            "step": int(self.metrics.counters["engine_steps"]) + 1,
            "failed": repr(exc),
            "programs": list(self._step_ev.get("programs", ())),
            "t_wall_ms": (round((time.perf_counter()
                                 - self._step_t0) * 1e3, 3)
                          if self._step_t0 is not None else None),
            "queue_depth": int(self.scheduler.queue_depth),
            "running": len(self.scheduler.running),
            "kv_used_pages": int(self.allocator.num_used),
            "kv_occupancy": round(float(self.allocator.occupancy()), 4),
        })
        self.last_snapshot = self.snapshot(reason=repr(exc))
        self.failed = True
        raise EngineFailure(
            f"unrecoverable engine error: {exc!r}; state drained to "
            f"snapshot ({len(self.last_snapshot['requests'])} requests)",
            snapshot=self.last_snapshot, cause=exc) from exc

    # ------------------------------------------------------------- step
    def step(self):
        """One engine iteration: cancellation sweep, schedule, run
        prefill chunks, run the batched decode step. Returns
        [(request_id, token)] in emission order (empty when idle).

        Failure semantics per launch: transients retried by the
        supervisor; a poison failure quarantines the offending
        request(s) and the step continues; anything else drains to a
        snapshot and raises EngineFailure."""
        if self.failed:
            raise EngineFailure("engine has failed; resume from "
                                "last_snapshot", snapshot=self.last_snapshot)
        emitted = []
        # flight recorder (ISSUE 10): per-step accumulator + counter
        # baseline for the deltas the step record reports
        self._step_t0 = time.perf_counter()
        self._step_ev = {"programs": []}
        _c = self.metrics.counters
        pre = {k: _c[k] for k in (
            "prefill_tokens", "requests_preempted", "step_retries",
            "requests_quarantined", "requests_aborted",
            "deadline_expired", "prefix_hits", "spec_drafted_tokens",
            "spec_accepted_tokens")}
        self._cancel_boundary()
        sched = self.scheduler.schedule()
        for req in sched.preempted:
            self.metrics.on_preempt()
            self._tr_preempt(req)

        for chunk in sched.prefills:
            req = chunk.request
            if req.state is RequestState.FINISHED:
                continue               # quarantined earlier this step
            if chunk.is_first:
                self.metrics.on_admission(req.request_id,
                                          req.cached_tokens,
                                          resumed=req.num_preemptions > 0)
                self._tr_admit(req, resumed=req.num_preemptions > 0)
            try:
                tok, ok = self._run_chunk(chunk)
            except Exception as exc:   # noqa: BLE001
                if classify_failure(exc) == POISON:
                    self._quarantine(req)
                    continue
                self._fail(exc)
            if not ok:
                self._quarantine(req)
                continue
            req.num_computed = chunk.start + chunk.length
            if chunk.is_last:
                reason = self._emit(req, int(tok), emitted)
                if reason is not None:
                    self.scheduler.finish(req, reason)
                    self._on_finished(req)
                elif self.role == "prefill" and not req.colocate:
                    # disaggregated prefill (ISSUE 18): the request's
                    # block-aligned pages donate to the radix tree and
                    # the request finishes "handoff" instead of joining
                    # the decode batch — the fleet pulls the pages to a
                    # decode-role worker via export_prefix. The first
                    # token was already emitted above, so the decode
                    # side resumes from index 1 with zero token loss.
                    req.handoff_prefix_len = \
                        self.scheduler.finish_handoff(req)
                    self.metrics.counters["prefill_handoffs"] += 1
                    self._on_finished(req)
                else:
                    self.scheduler.on_prefilled(req)

        decodes = [r for r in sched.decodes
                   if r.state is not RequestState.FINISHED]
        if decodes:
            for req in decodes:
                self._apply_copies(req.pending_copies)
                req.pending_copies = []
            if self.proposer is not None:
                self._spec_decode_step(decodes, emitted)
            elif self.decode_steps > 1:
                self._multi_decode_step(decodes, emitted)
            else:
                self._plain_decode_step(decodes, emitted)

        self.metrics.on_step()
        self.metrics.update_gauges(
            queue_depth=self.scheduler.queue_depth,
            running=len(self.scheduler.running),
            kv_used_pages=self.allocator.num_used,
            kv_occupancy=self.allocator.occupancy(),
            cached_pages=self.radix.num_cached_pages if self.radix else 0,
            radix_nodes=self.radix.num_nodes if self.radix else 0,
            radix_evicted_pages=(self.radix.num_evicted_pages
                                 if self.radix else None),
            **self._spill_gauges())
        self._record_step(pre, n_chunks=len(sched.prefills),
                          n_decode=len(decodes), n_emitted=len(emitted))
        return emitted

    def _record_step(self, pre: Dict[str, int], *, n_chunks: int,
                     n_decode: int, n_emitted: int):
        """Append this iteration's StepRecord to the flight recorder.
        Idle steps (nothing scheduled, nothing cancelled) are skipped so
        a quiet polling loop cannot evict the history that matters."""
        c = self.metrics.counters
        rec = {
            "step": int(c["engine_steps"]),
            "t_wall_ms": round((time.perf_counter()
                                - self._step_t0) * 1e3, 3),
            "programs": list(self._step_ev["programs"]),
            "prefill_chunks": int(n_chunks),
            "prefill_tokens": int(c["prefill_tokens"]
                                  - pre["prefill_tokens"]),
            "decode_batch": int(n_decode),
            # tokens-per-launch context under coarser launches
            # (ISSUE 13): K=1 for the plain decode program, the launch
            # K bucket for multi-step decode, K+1 for a speculative
            # verify launch, 0 for no decode-side launch this step
            "decode_k": int(self._step_ev.get("decode_k", 0))
            if n_decode else 0,
            "tokens_out": int(n_emitted),
            "preempted": int(c["requests_preempted"]
                             - pre["requests_preempted"]),
            "retries": int(c["step_retries"] - pre["step_retries"]),
            "quarantined": int(c["requests_quarantined"]
                               - pre["requests_quarantined"]),
            "aborted": int(c["requests_aborted"]
                           - pre["requests_aborted"]),
            "expired": int(c["deadline_expired"]
                           - pre["deadline_expired"]),
            "prefix_hits": int(c["prefix_hits"] - pre["prefix_hits"]),
            "spec_drafted": int(c["spec_drafted_tokens"]
                                - pre["spec_drafted_tokens"]),
            "spec_accepted": int(c["spec_accepted_tokens"]
                                 - pre["spec_accepted_tokens"]),
            "queue_depth": int(self.scheduler.queue_depth),
            "running": len(self.scheduler.running),
            "kv_used_pages": int(self.allocator.num_used),
            "kv_occupancy": round(float(self.allocator.occupancy()), 4),
            "cached_pages": int(self.radix.num_cached_pages
                                if self.radix else 0),
        }
        if rec["programs"] or any(
                rec[k] for k in ("prefill_chunks", "decode_batch",
                                 "tokens_out", "preempted", "aborted",
                                 "expired", "quarantined")):
            self.recorder.record(rec)

    def timeline(self) -> List[dict]:
        """Flight-recorder view: the last N non-idle StepRecords,
        oldest first (ISSUE 10). The same list rides every snapshot."""
        return self.recorder.records()

    def _plain_decode_step(self, decodes: List[Request], emitted):
        """One batched single-token decode launch + emission (the
        non-speculative path, unchanged semantics)."""
        degraded = False
        try:
            toks, oks = self._run_decode(decodes)
        except Exception as exc:   # noqa: BLE001
            if classify_failure(exc) == POISON:
                # unattributed poison (a FloatingPointError raised
                # by an eager/dispatch NaN hook instead of the
                # in-graph flags): isolate by running rows solo
                toks, oks = self._isolate_poisoned(decodes)
                degraded = True
            else:
                self._fail(exc)
        n0 = len(emitted)
        for i, req in enumerate(decodes):
            if not oks[i]:
                self._quarantine(req)
                continue
            reason = self._emit(req, int(toks[i]), emitted)
            if reason is not None:
                self.scheduler.finish(req, reason)
                self._on_finished(req)
        if not degraded:
            # TPOT sample: launch wall seconds / tokens emitted, so the
            # per-token percentiles stay comparable across K (ISSUE 13)
            self.metrics.on_decode_launch(1, len(decodes),
                                          len(emitted) - n0,
                                          self._last_launch_s)
        else:
            # solo isolation launches counted decode_tokens in
            # _run_decode; keep the tokens-per-launch denominator
            # honest (no TPOT sample — solo timings aren't a batch
            # launch's)
            self.metrics.on_decode_launch(1, len(decodes), 0, None)

    def _isolate_poisoned(self, reqs: List[Request]):
        """Degraded mode for an UNATTRIBUTED poison failure of a decode
        batch: re-run each row as a solo launch to find the poisoned
        request(s), returning (toks, oks) for the caller to emit or
        quarantine from. Solo launches are idempotent K/V-wise (same
        tokens written at the same positions) but use the B=1 bucket —
        a different program shape, so this path trades the cross-shape
        bit-identity guarantee for failure isolation (greedy tokens in
        practice agree; SERVING.md documents the caveat)."""
        toks = np.zeros((len(reqs),), np.int64)
        oks = np.ones((len(reqs),), bool)
        for i, req in enumerate(reqs):
            try:
                t, o = self._run_decode([req])
            except Exception as exc:   # noqa: BLE001
                if classify_failure(exc) == POISON:
                    oks[i] = False
                    continue
                self._fail(exc)
            toks[i] = int(t[0])
            oks[i] = bool(o[0])
        return toks, oks

    def _retain(self, req: Request):
        """Terminal-request retention bookkeeping (bounded window).
        Every terminal path funnels here, so it doubles as the
        proposer's release hook (a KV-owning proposer frees its draft
        pages for this request) and the adapter-refcount release
        (ISSUE 15: a terminal request unpins its adapter, making it
        eviction-eligible again once idle)."""
        if self.proposer is not None:
            self.proposer.on_finished(req)
        if self.lora is not None and req.adapter is not None:
            self.lora.release(req.adapter)
        self._finished_order.append(req.request_id)
        while len(self._finished_order) > self.max_retained_finished:
            self.requests.pop(self._finished_order.pop(0), None)
            self.num_evicted_finished += 1

    def _on_finished(self, req: Request):
        self.metrics.on_finish(req.request_id)
        self._tr_finish(req.request_id, req.finish_reason or "stop")
        self._retain(req)

    # --------------------------------------------------- snapshot/resume
    def snapshot(self, reason: str = "requested", *,
                 include_recorder: bool = True) -> dict:
        """Serializable drain state: every non-finished request (queued,
        mid-prefill, decoding, preempted) with its prompt, tokens
        generated so far, and remaining deadline. Device state (KV
        pages) is deliberately NOT captured — it is lost with the device
        anyway; a resumed request re-prefills prompt+generated exactly
        like a preemption resume, so greedy outputs stay bit-identical
        under the same bucket grid. JSON-roundtrip-safe by construction
        (plain ints/floats/lists only). `include_recorder=False` drops
        the flight-recorder ring — the cross-process worker's
        heartbeats ship a snapshot ~20x/s and the supervisor only reads
        the request records, so the postmortem payload stays on the
        drain/failure snapshots where it is read."""
        now = self._now()
        recs = []
        for req in self.requests.values():
            if req.state is RequestState.FINISHED:
                continue
            recs.append({
                "request_id": int(req.request_id),
                "prompt_ids": [int(t) for t in req.prompt_ids],
                "output_ids": [int(t) for t in req.output_ids],
                "max_new_tokens": int(req.max_new_tokens),
                "eos_token_id": (None if req.eos_token_id is None
                                 else int(req.eos_token_id)),
                "num_preemptions": int(req.num_preemptions),
                "aborted": bool(req.aborted),
                "deadline_remaining_s": (
                    None if req.deadline is None
                    else float(req.deadline - now)),
                # ISSUE 15 (snapshot minor 2): the adapter rides the
                # record so failover re-lands the request WITH its
                # adapter (or refuses typed) — never wrong-adapter
                "adapter": req.adapter,
                # ISSUE 18 (snapshot minor 3): a supervisor-pinned
                # colocate flag survives migration — a role-starved
                # fallback must stay decodable wherever it re-lands
                "colocate": bool(req.colocate),
            })
        recs.sort(key=lambda r: r["request_id"])   # FCFS order on resume
        snap = {"version": SNAPSHOT_VERSION, "minor": SNAPSHOT_MINOR,
                "reason": str(reason),
                "rng_key": np.asarray(self._key).tolist(),
                "requests": recs}
        if include_recorder:
            # the engine's last N non-idle StepRecords ride every
            # snapshot (ISSUE 10): an engine_failures postmortem
            # reads the context straight out of the drain state.
            # from_snapshot/adopt ignore the key, so the schema
            # version is unchanged — old snapshots resume fine.
            snap["flight_recorder"] = self.recorder.records()
        return snap

    def _restore_request(self, rec: dict) -> Request:
        """Rebuild one snapshot request record into THIS engine under
        its ORIGINAL id: generated tokens fold into the resume prompt
        (the preemption recompute path), the remaining deadline is
        re-anchored on this engine's clock, and the admission bound is
        bypassed (restored work was already admitted once — shedding it
        would drop accepted work). An adapter'd record REQUIRES its
        adapter loaded here (typed AdapterNotLoaded otherwise): a
        migrated request must re-land with the adapter or not at all —
        the fleet parks it typed, never serves the wrong weights."""
        adapter = rec.get("adapter")
        if adapter is not None and (self.lora is None
                                    or not self.lora.has(adapter)):
            self.metrics.counters["adapter_rejects"] += 1
            raise AdapterNotLoaded(
                f"snapshot request {rec['request_id']} needs adapter "
                f"{adapter!r}, which this engine does not hold",
                adapter=adapter)
        req = Request(rec["prompt_ids"], rec["max_new_tokens"],
                      rec.get("eos_token_id"),
                      request_id=rec["request_id"], adapter=adapter)
        if len(req.prompt_ids) + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"snapshot request {req.request_id} needs "
                f"{len(req.prompt_ids) + req.max_new_tokens} tokens "
                f"> resumed engine max_seq_len {self.max_seq_len}")
        req.output_ids = [int(t) for t in rec.get("output_ids", [])]
        req.num_preemptions = int(rec.get("num_preemptions", 0))
        req.aborted = bool(rec.get("aborted", False))
        req.colocate = bool(rec.get("colocate", False))
        rem = rec.get("deadline_remaining_s")
        if rem is not None:
            req.deadline = self._now() + float(rem)
        self.scheduler.add_request(req, force=True)
        if adapter is not None:
            self.lora.acquire(adapter)     # pinned until terminal
            # THIS engine's load generation namespaces the radix key —
            # the adopting registry's weights are what will serve it
            req.adapter_key = self.lora.namespace_of(adapter)
        self.requests[req.request_id] = req
        # adopted, not added: a migrated request already counted as an
        # arrival on its original engine, and fleet summaries merge
        # counters across ALL replicas (dead ones included)
        self.metrics.on_adopt(req.request_id)
        if self.tracer is not None:
            # with a fleet-shared tracer the migrated request's LIVE
            # trace continues here (begin is idempotent); a fresh
            # from_snapshot engine starts a new one at the adopt mark
            tr = self.tracer.begin(req.request_id,
                                   engine=self.metrics.name,
                                   prompt_len=len(req.prompt_ids),
                                   max_new_tokens=req.max_new_tokens)
            now = self.tracer.now_ns()
            tr.mark("adopt", now, engine=self.metrics.name,
                    tokens_so_far=len(req.output_ids))
            tr.t_queue = now      # re-queued on the adopting engine
        return req

    def adopt_requests(self, recs) -> List[int]:
        """Live-migration intake: restore snapshot request records into
        this RUNNING engine (the fleet re-lands a dead or draining
        replica's work on survivors this way — `from_snapshot` minus
        the fresh-engine construction). Requests keep their original
        ids (unique process-wide: ids come from one global counter, and
        the counter is bumped past restored ids for the cross-process
        case). Greedy continuations are bit-identical to an
        uninterrupted run under the same bucket grid; this engine's OWN
        rng key stream serves any sampled continuation. Returns the
        adopted request ids."""
        if self.failed:
            raise EngineFailure("engine has failed; resume from "
                                "last_snapshot",
                                snapshot=self.last_snapshot)
        ids = []
        for rec in recs:
            ids.append(self._restore_request(rec).request_id)
        if ids:
            bump_request_counter(max(ids))
        return ids

    def vacate(self, reason: str = "migrated") -> int:
        """Release every KV page this engine holds: cancel all
        non-finished requests locally (no donation — the work is not
        lost, it re-lands elsewhere via `adopt_requests`; no
        abort/expired metrics for the same reason) and drop the radix
        tree. Pure host bookkeeping, so it works on a FAILED engine —
        the fleet calls this on a dead replica's pool and then asserts
        full page/refcount reclamation. Returns pages freed."""
        before = self.allocator.num_free
        for req in list(self.requests.values()):
            if req.state is not RequestState.FINISHED:
                if self.scheduler.cancel(req, reason, donate=False):
                    self._retain(req)
        self.reset_prefix_cache()
        # refresh the metric gauges NOW: a vacated (usually dead) engine
        # never steps again, so without this its last mid-flight gauges
        # would sit in every future fleet-merged summary as phantom
        # queue depth / used pages
        self.metrics.update_gauges(
            queue_depth=self.scheduler.queue_depth,
            running=len(self.scheduler.running),
            kv_used_pages=self.allocator.num_used,
            kv_occupancy=self.allocator.occupancy(),
            cached_pages=self.radix.num_cached_pages if self.radix else 0,
            radix_nodes=self.radix.num_nodes if self.radix else 0,
            radix_evicted_pages=(self.radix.num_evicted_pages
                                 if self.radix else None),
            **self._spill_gauges())
        return self.allocator.num_free - before

    @classmethod
    def from_snapshot(cls, model, snapshot: dict, **engine_kw):
        """Build a fresh engine that resumes a drained one. Restored
        requests keep their ORIGINAL ids (the global id counter is
        bumped past them) and re-enter WAITING with their generated
        tokens folded into the resume prompt — the same recompute path
        a preemption uses. Greedy outputs complete bit-identically
        given the same bucket grid; the sampled-path key stream is
        restored but its position reflects the resume's chunking, so
        sampled continuations are reproducible per snapshot, not
        bit-equal to the uninterrupted run. Raises the typed
        `SnapshotVersionError` on a schema-version mismatch — resuming
        a snapshot this build would misread must fail loud."""
        check_snapshot_version(snapshot)
        eng = cls(model, **engine_kw)
        eng._key = jnp.asarray(np.asarray(snapshot["rng_key"], np.uint32))
        eng.adopt_requests(snapshot["requests"])
        return eng

    # --------------------------------------------------- prefix cache ops
    def reset_prefix_cache(self) -> int:
        """Drop every cached prefix (the tree's page refs release);
        returns the number of pages returned to the free list. With no
        live requests this brings allocator occupancy back to zero —
        the drain-reclamation check in the acceptance test."""
        if self.radix is None:
            return 0
        return self.radix.clear()

    # -------------------------------- fleet prefix sharing (ISSUE 17)
    def export_prefix(self, tokens) -> tuple:
        """Fleet KV pull, DONOR side: the longest DEVICE-resident
        cached prefix of `tokens` as (num_tokens, [payload bytes, one
        per page]). The payloads are the same CRC-protected codec the
        spill tier demotes with, so they chunk straight into PR-14
        mailbox frames. promote_budget=0 pins the walk to the device
        tier — a pull must never charge this engine's own prefill
        budget or its device pool for a sibling's benefit. The LRU bump
        is deliberate: a pulled prefix is hot."""
        if self.radix is None:
            return 0, []
        pages, m = self.radix.match(tokens, promote_budget=0)
        if not pages:
            return 0, []
        payloads = [self._gather_page_payload(pid) for pid in pages]
        self.metrics.counters["kv_pages_exported"] += len(payloads)
        return m, payloads

    def adopt_prefix(self, tokens, payloads) -> int:
        """Fleet KV pull, RECEIVER side: land a sibling's exported
        prefix pages in this engine's caches and donate them to the
        radix tree (so the next admission matches them like any local
        prefix). Degrades to 0 — never raises — on a corrupt payload,
        a dry device pool, or a span the tree already holds: a failed
        pull just means the prefix recomputes, exactly the spill tier's
        fallback contract. Returns pages newly adopted."""
        if self.radix is None or not payloads:
            return 0
        n = min(len(payloads) * self.page_size,
                (len(tokens) // self.page_size) * self.page_size)
        payloads = payloads[:n // self.page_size]
        if not payloads:
            return 0
        try:
            arrays = [decode_page_payload(p) for p in payloads]
        except HostPageCorrupt:
            self.metrics.counters["host_spill_corrupt"] += 1
            return 0
        try:
            pids = self.allocator._alloc_pages(len(arrays))
        except BlocksExhausted:
            return 0
        try:
            for pid, arrs in zip(pids, arrays):
                self._scatter_page_payload(pid, arrs)
        except HostPageCorrupt:
            self.metrics.counters["host_spill_corrupt"] += 1
            for pid in pids:
                self.allocator._decref(pid)
            return 0
        adopted = self.radix.insert(tuple(tokens[:n]), pids)
        # the tree took its own refs on the pages it adopted; drop the
        # intake refs — duplicate pages (spans already cached) free here
        for pid in pids:
            self.allocator._decref(pid)
        self.metrics.counters["kv_pages_adopted"] += adopted
        return adopted

    def release_prefix(self, tokens, *, drop: bool = False) -> int:
        """Release-after-handoff page accounting (ISSUE 18): once this
        engine's pages for `tokens` were shipped to AND adopted by a
        decode-role sibling, the local copy stops earning its pool
        space on its own merits. Default: DEMOTE the cached span to
        coldest LRU rank — it stays matchable (a shared prompt prefix
        keeps serving future admissions, and a later match re-heats
        it), but it is the FIRST eviction victim under pressure, so a
        prefill-role pool can never fill with spans that already live
        on decode workers. `drop=True` frees the deepest childless
        nodes of the span outright (strict accounting — tests assert
        exact reclamation with it). Returns pages demoted/freed."""
        if self.radix is None:
            return 0
        chain = [child for child, _ in self.radix._walk_prefix(tokens)]
        released = 0
        if drop:
            before = self.allocator.num_free
            for node in reversed(chain):
                # only childless device-resident tails: dropping an
                # interior node would orphan descendants reachable by
                # other requests' prefixes
                if node.children or node.host_pages:
                    break
                self.radix._drop_node(node)
            released = self.allocator.num_free - before
        else:
            for node in chain:
                node.last_use = 0       # coldest: first eviction victim
                released += len(node.pages)
        self.metrics.counters["kv_pages_released"] += released
        return released

    # ------------------------------------------------------- convenience
    def stream(self):
        """Generator over (request_id, token) until all work drains."""
        while self.has_work():
            for item in self.step():
                yield item

    def run(self) -> Dict[int, List[int]]:
        """Drain everything; returns {request_id: generated tokens} for
        every request alive when run() was called — tokens are collected
        from step() emissions, so results survive even when the bounded
        finished-retention window evicts the Request object mid-drain."""
        out = {rid: list(r.output_ids) for rid, r in self.requests.items()}
        guard = 0
        limit = 16 * (self.max_seq_len + 2) * max(1, len(self.requests))
        while self.has_work():
            for rid, tok in self.step():
                out.setdefault(rid, []).append(tok)
            guard += 1
            if guard > limit:
                raise RuntimeError("serving engine failed to drain "
                                   f"after {guard} steps")
        return out

    def shutdown(self):
        if self.proposer is not None:
            self.proposer.reset()
        self.metrics.unregister()
