"""ServingEngine: continuous-batching inference over the paged-KV kernels.

The XLA-shaped answer to Orca/vLLM-style serving: iteration-level
scheduling and block-based KV management run on the host (scheduler.py /
kv_cache.py), while all device work funnels through a SMALL, FIXED set of
compiled programs — one per shape bucket — so continuous batching never
triggers unbounded recompilation:

  * prefill program, keyed by (prompt-length bucket): runs the model's
    ordinary cached forward (via jit.api.functional_call — the same
    state-swap machinery to_static/jit.save use) on ONE padded prompt,
    scatters the resulting per-layer K/V into the paged cache with
    `paged_cache_write_range`, and samples the first token;
  * decode program, keyed by (batch bucket, block-table-width bucket):
    one batched step through `model.forward_paged_decode` — per-row rope
    positions, `paged_cache_write` of the current token, Pallas
    `paged_attention_decode` over the block tables — plus sampling.

Shape buckets pad up: a prompt of 19 tokens runs in the 32-bucket, a
decode batch of 5 in the 8-bucket. The recompile counter (metrics) is
bounded by the bucket grid, which the engine test asserts.

Determinism contract: greedy decode is deterministic, and a request's
tokens are bit-identical whether it runs alone or batched with others —
PROVIDED the same shape buckets are hit (XLA does not promise identical
rounding across different program shapes; rows within one program are
independent). The acceptance test pins one decode bucket for exactly
this reason. Sampled decode draws from one engine-level key stream and
is reproducible per (engine seed, arrival order) but not across
different interleavings.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..jit.api import functional_call
from ..models.generation import _sample_arr
from .kv_cache import BlockAllocator, PAD_PAGE
from .metrics import ServingMetrics
from .scheduler import Request, RequestState, Scheduler

__all__ = ["ServingEngine"]

_engine_counter = itertools.count()


def _bucket_for(value: int, buckets: List[int]) -> int:
    for b in buckets:
        if value <= b:
            return b
    raise ValueError(f"{value} exceeds largest bucket {buckets[-1]}")


def _pow2_buckets(lo: int, hi: int) -> List[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


class ServingEngine:
    """Continuous-batching engine over a causal LM with paged-KV decode.

    model: a LlamaForCausalLM-protocol model — `forward(ids, caches=...)`
    for prefill and `forward_paged_decode(ids, paged_caches,
    block_tables, seq_lens)` for batched decode.
    """

    def __init__(self, model, *, num_pages: int = 128, page_size: int = 16,
                 max_batch_size: int = 8, token_budget: int = 512,
                 batch_buckets: Optional[List[int]] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 pages_buckets: Optional[List[int]] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 max_retained_finished: int = 1024):
        cfg = model.cfg
        self.model = model
        self.cfg = cfg
        self.num_layers = cfg.num_hidden_layers
        self.num_kv = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self._key = jax.random.PRNGKey(seed)

        # serving weights are immutable: snapshot the flat {name: array}
        # view once instead of re-walking state_dict() every step
        self._state = {k: t._data for k, t in model.state_dict().items()}

        # fail at construction, not at the first decode launch: the
        # Pallas kernel's static constraints are model geometry
        from ..kernels.paged_attention import check_supported_paged
        dtype = next(iter(self._state.values())).dtype
        self._cache_dtype = dtype
        check_supported_paged(
            (1, cfg.num_attention_heads, self.head_dim),
            (self.num_pages, self.num_kv, self.page_size, self.head_dim),
            dtype)

        # longest sequence a request may ever reach (rope table and page
        # supply both bound it)
        self.max_seq_len = min(int(cfg.max_position_embeddings),
                               (self.num_pages - 1) * self.page_size)
        max_pages_per_seq = -(-self.max_seq_len // self.page_size)

        self.batch_buckets = sorted(batch_buckets or
                                    _pow2_buckets(1, int(max_batch_size)))
        self.prefill_buckets = sorted(
            prefill_buckets or _pow2_buckets(
                min(16, self.max_seq_len), self.max_seq_len))
        self.pages_buckets = sorted(
            pages_buckets or _pow2_buckets(
                min(2, max_pages_per_seq), max_pages_per_seq))
        # the widest block table a decode program supports also bounds
        # how long any sequence may grow
        self.max_seq_len = min(self.max_seq_len,
                               self.pages_buckets[-1] * self.page_size)
        if self.prefill_buckets[-1] > self.max_seq_len:
            raise ValueError("prefill bucket exceeds max sequence length")

        self.allocator = BlockAllocator(self.num_pages, self.page_size)
        self.scheduler = Scheduler(
            self.allocator, max_batch_size=self.batch_buckets[-1],
            token_budget=token_budget,
            max_prompt_len=self.prefill_buckets[-1])
        # per-engine provider name: two live engines must not shadow each
        # other in profiler.counters(), nor unregister each other
        self.metrics = ServingMetrics(
            name=f"serving-{next(_engine_counter)}").register()

        shape = (self.num_pages, self.num_kv, self.page_size, self.head_dim)
        self._k_caches = [jnp.zeros(shape, dtype)
                          for _ in range(self.num_layers)]
        self._v_caches = [jnp.zeros(shape, dtype)
                          for _ in range(self.num_layers)]

        self.requests: Dict[int, Request] = {}
        self._finished_order: List[int] = []
        # a long-lived server must not accumulate every finished request
        # (same unbounded-growth class as the jit fallback registry):
        # only the most recent `max_retained_finished` stay readable
        self.max_retained_finished = int(max_retained_finished)
        self.num_evicted_finished = 0
        self._programs: Dict[tuple, object] = {}
        # caches only pay off donated on a real accelerator; CPU jit
        # warns per call and keeps the copy anyway
        self._donate = (1, 2) if jax.default_backend() == "tpu" else ()

    # ------------------------------------------------------------- intake
    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None) -> int:
        req = Request(prompt_ids, max_new_tokens, eos_token_id)
        if len(req.prompt_ids) + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {len(req.prompt_ids)} + max_new_tokens "
                f"{req.max_new_tokens} exceeds max_seq_len "
                f"{self.max_seq_len}")
        # recompute preemption re-prefills prompt+generated, which can
        # reach prompt + max_new - 1 tokens — every possible resume must
        # fit the prefill bucket grid, or a preemption could strand the
        # request un-resumable mid-flight
        worst_resume = len(req.prompt_ids) + req.max_new_tokens - 1
        if worst_resume > self.prefill_buckets[-1]:
            raise ValueError(
                f"prompt {len(req.prompt_ids)} + max_new_tokens "
                f"{req.max_new_tokens} could resume at {worst_resume} "
                f"tokens after a preemption > largest prefill bucket "
                f"{self.prefill_buckets[-1]}; widen prefill_buckets or "
                f"lower max_new_tokens")
        self.requests[req.request_id] = req
        self.scheduler.add_request(req)
        self.metrics.on_add(req.request_id)
        return req.request_id

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    # ------------------------------------------------------ program cache
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _get_program(self, key, builder):
        prog = self._programs.get(key)
        if prog is None:
            prog = builder()
            self._programs[key] = prog
            self.metrics.on_recompile()
        return prog

    @property
    def num_compiled_programs(self) -> int:
        return len(self._programs)

    def max_program_count(self) -> int:
        """The bucket-grid bound the recompile counter can never exceed."""
        return (len(self.prefill_buckets)
                + len(self.batch_buckets) * len(self.pages_buckets))

    # ---------------------------------------------------------- prefill
    def _build_prefill(self, S: int):
        """One padded prompt -> paged cache + first sampled token."""
        L, KV, D = self.num_layers, self.num_kv, self.head_dim
        model, dtype = self.model, self._cache_dtype
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        def program(state, kcs, vcs, ids, true_len, bt, key):
            st = {k: Tensor(v) for k, v in state.items()}
            empty = [(Tensor(jnp.zeros((1, 0, KV, D), dtype)),
                      Tensor(jnp.zeros((1, 0, KV, D), dtype)))
                     for _ in range(L)]
            logits, caches = functional_call(model, st, Tensor(ids),
                                             caches=empty)
            from ..kernels.paged_attention import paged_cache_write_range
            new_kcs, new_vcs = [], []
            for l in range(L):
                k_seq = caches[l][0]._data[0]        # (S, KV, D), roped
                v_seq = caches[l][1]._data[0]
                kc, vc = paged_cache_write_range(kcs[l], vcs[l], k_seq,
                                                 v_seq, bt, true_len)
                new_kcs.append(kc)
                new_vcs.append(vc)
            last = logits._data[0, true_len - 1]      # (V,) at prompt end
            tok = _sample_arr(last[None], key, temperature, top_k, top_p)[0]
            return tok, new_kcs, new_vcs

        return jax.jit(program, donate_argnums=self._donate)

    def _run_prefill(self, req: Request):
        from .. import profiler
        ids = req.resume_ids
        n = len(ids)
        S = _bucket_for(n, self.prefill_buckets)
        prog = self._get_program(("prefill", S),
                                 lambda: self._build_prefill(S))
        P = -(-S // self.page_size)                  # table rows the
        bt = np.full((P,), PAD_PAGE, np.int32)       # scatter may index
        bt[:len(req.seq.pages)] = req.seq.pages
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = ids
        with profiler.RecordEvent("serving.prefill"), no_grad():
            tok, self._k_caches, self._v_caches = prog(
                self._state, self._k_caches, self._v_caches,
                jnp.asarray(padded), jnp.int32(n), jnp.asarray(bt),
                self._next_key())
        self.metrics.on_prefill(n)
        return int(tok)

    # ----------------------------------------------------------- decode
    def _build_decode(self, B: int, P: int):
        """One batched token step over the paged caches."""
        model = self.model
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        def program(state, kcs, vcs, ids, bt, sl, key):
            st = {k: Tensor(v) for k, v in state.items()}
            paged = [(Tensor(kcs[l]), Tensor(vcs[l]))
                     for l in range(len(kcs))]
            logits, caches = functional_call(
                model, st, Tensor(ids), paged, Tensor(bt), Tensor(sl),
                method="forward_paged_decode")
            toks = _sample_arr(logits._data[:, 0, :], key, temperature,
                               top_k, top_p)
            return (toks, [c[0]._data for c in caches],
                    [c[1]._data for c in caches])

        return jax.jit(program, donate_argnums=self._donate)

    def _run_decode(self, reqs: List[Request]):
        from .. import profiler
        B = _bucket_for(len(reqs), self.batch_buckets)
        max_pages = max(len(r.seq.pages) for r in reqs)
        P = _bucket_for(max_pages, self.pages_buckets)
        prog = self._get_program(("decode", B, P),
                                 lambda: self._build_decode(B, P))
        ids = np.zeros((B, 1), np.int32)
        sl = np.zeros((B,), np.int32)
        seqs = [r.seq for r in reqs]
        bt = np.full((B, P), PAD_PAGE, np.int32)
        bt[:len(reqs)] = self.allocator.block_table(seqs, P)
        for i, r in enumerate(reqs):
            ids[i, 0] = r.output_ids[-1]
            sl[i] = r.seq.num_tokens
        with profiler.RecordEvent("serving.decode_step"), no_grad():
            toks, self._k_caches, self._v_caches = prog(
                self._state, self._k_caches, self._v_caches, jnp.asarray(ids),
                jnp.asarray(bt), jnp.asarray(sl), self._next_key())
        self.metrics.on_decode(len(reqs))
        return np.asarray(toks)

    # ---------------------------------------------------- CoW page copies
    def _apply_copies(self, copies):
        for src, dst in copies:
            for l in range(self.num_layers):
                self._k_caches[l] = self._k_caches[l].at[dst].set(
                    self._k_caches[l][src])
                self._v_caches[l] = self._v_caches[l].at[dst].set(
                    self._v_caches[l][src])

    # ------------------------------------------------------------- step
    def _emit(self, req: Request, tok: int, emitted):
        """Record one generated token + run the finish checks."""
        first = req.num_generated == 0
        req.output_ids.append(tok)
        if first:
            self.metrics.on_first_token(req.request_id)
        emitted.append((req.request_id, tok))
        if req.eos_token_id is not None and tok == req.eos_token_id:
            return "stop"
        if req.remaining_new_tokens() <= 0:
            return "length"
        return None

    def step(self):
        """One engine iteration: schedule, prefill admitted prompts,
        run the batched decode step. Returns [(request_id, token)] in
        emission order (empty when idle)."""
        emitted = []
        sched = self.scheduler.schedule()
        for req in sched.preempted:
            self.metrics.on_preempt()

        for req in sched.prefills:
            tok = self._run_prefill(req)
            reason = self._emit(req, tok, emitted)
            if reason is not None:
                self.scheduler.finish(req, reason)
                self._on_finished(req)
            else:
                self.scheduler.on_prefilled(req)

        if sched.decodes:
            for req in sched.decodes:
                self._apply_copies(req.pending_copies)
                req.pending_copies = []
            toks = self._run_decode(sched.decodes)
            for i, req in enumerate(sched.decodes):
                reason = self._emit(req, int(toks[i]), emitted)
                if reason is not None:
                    self.scheduler.finish(req, reason)
                    self._on_finished(req)

        self.metrics.on_step()
        self.metrics.update_gauges(
            queue_depth=self.scheduler.queue_depth,
            running=len(self.scheduler.running),
            kv_used_pages=self.allocator.num_used,
            kv_occupancy=self.allocator.occupancy())
        return emitted

    def _on_finished(self, req: Request):
        self.metrics.on_finish(req.request_id)
        self._finished_order.append(req.request_id)
        while len(self._finished_order) > self.max_retained_finished:
            self.requests.pop(self._finished_order.pop(0), None)
            self.num_evicted_finished += 1

    # ------------------------------------------------------- convenience
    def stream(self):
        """Generator over (request_id, token) until all work drains."""
        while self.has_work():
            for item in self.step():
                yield item

    def run(self) -> Dict[int, List[int]]:
        """Drain everything; returns {request_id: generated tokens} for
        every request alive when run() was called — tokens are collected
        from step() emissions, so results survive even when the bounded
        finished-retention window evicts the Request object mid-drain."""
        out = {rid: list(r.output_ids) for rid, r in self.requests.items()}
        guard = 0
        limit = 16 * (self.max_seq_len + 2) * max(1, len(self.requests))
        while self.has_work():
            for rid, tok in self.step():
                out.setdefault(rid, []).append(tok)
            guard += 1
            if guard > limit:
                raise RuntimeError("serving engine failed to drain "
                                   f"after {guard} steps")
        return out

    def shutdown(self):
        self.metrics.unregister()
