"""Step supervisor: failure classification + retry policy for compiled
engine launches.

This repo's own chip history is the spec (CLAUDE.md round-4 notes): the
axon relay dies and comes back, a wedged device returns UNAVAILABLE for
minutes, and `bench.py` is REQUIRED to never exit non-zero. Device-level
faults are the normal case on this hardware, so the engine treats every
compiled-step launch as fallible and sorts failures into three bins:

* **transient** — UNAVAILABLE / relay / connection-class transport
  errors (and the typed `TransientDeviceError` the fault harness
  raises). Retried in place with capped exponential backoff; the batch
  re-runs bit-identically because launches are idempotent (a chunk or
  decode step rewrites the same K/V at the same positions, and the
  engine draws each launch's RNG key BEFORE the supervised call).
* **poison** — deterministic numeric failure (FloatingPointError, i.e.
  the `utils.nan_inf` dispatch-hook contract, incl. the typed
  `PoisonedComputation`). Retrying cannot help; the engine quarantines
  the offending request(s) and keeps the rest of the batch alive.
* **fatal** — everything else (deterministic OOM/INVALID_ARGUMENT,
  exhausted retries). The engine drains to a snapshot and raises
  `EngineFailure`.

Classification is by exception type first, then by status-code markers
in the message — the same markers jaxlib's XlaRuntimeError carries, so
no import of jaxlib internals is needed.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from .errors import PoisonedComputation, TransientDeviceError

__all__ = ["classify_failure", "RetryPolicy", "StepSupervisor",
           "TRANSIENT", "POISON", "FATAL"]

TRANSIENT = "transient"
POISON = "poison"
FATAL = "fatal"

# Status-code markers of retryable transport failures. DEADLINE_EXCEEDED
# and the relay/socket strings cover the axon stdio relay dying
# mid-call; RESOURCE_EXHAUSTED (device OOM) is deliberately NOT here —
# re-launching the identical program re-OOMs deterministically.
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                      "relay", "connection reset", "connection refused",
                      "socket closed", "Connection reset")


def classify_failure(exc: BaseException) -> str:
    """Sort an exception from a compiled-step launch into
    transient / poison / fatal. An exception that carries its own
    `failure_class` attribute (the fleet transport's typed
    `TransportError`, ISSUE 14) is believed verbatim — the raiser
    knows whether a retry can help better than a message heuristic
    does — as long as it names one of the three bins."""
    own = getattr(exc, "failure_class", None)
    if own in (TRANSIENT, POISON, FATAL):
        return own
    if isinstance(exc, (PoisonedComputation, FloatingPointError)):
        return POISON
    if isinstance(exc, TransientDeviceError):
        return TRANSIENT
    msg = str(exc)
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return FATAL


class RetryPolicy:
    """Capped exponential backoff: delays base, base*factor, ... capped
    at `cap_s`, at most `max_retries` re-launches. `sleep` is injectable
    so tests and the soak harness never wall-clock-wait."""

    def __init__(self, max_retries: int = 3, base_s: float = 0.05,
                 factor: float = 2.0, cap_s: float = 2.0,
                 sleep: Optional[Callable[[float], None]] = None):
        self.max_retries = int(max_retries)
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.cap_s = float(cap_s)
        self.sleep = sleep if sleep is not None else time.sleep

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based)."""
        return min(self.cap_s, self.base_s * (self.factor ** (attempt - 1)))


class StepSupervisor:
    """Wraps compiled-step launches; owns the retry loop and counters.

    `run(launch)` returns the launch's result, retrying transients per
    the policy. Poison and fatal failures propagate to the engine (which
    quarantines or snapshots — those decisions need request context the
    supervisor does not have). `on_retry` is the metrics hook.

    `retryable` (optional callable) is consulted before every retry: a
    False return re-raises instead. The engine uses it for the donated-
    buffer hazard: on TPU the K/V caches are donated to the launch, and
    a dispatch that failed AFTER consuming them leaves nothing valid to
    re-pass — retrying would hit 'Array has been deleted'; failing to
    the snapshot path (which recomputes KV on resume) is the only
    correct move."""

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 on_retry: Optional[Callable[[str, int], None]] = None,
                 retryable: Optional[Callable[[], bool]] = None):
        self.policy = policy or RetryPolicy()
        self.on_retry = on_retry
        self.retryable = retryable
        self.num_retries = 0
        self.last_error: Optional[BaseException] = None

    def run(self, launch: Callable, *, label: str = "step"):
        attempt = 0
        while True:
            try:
                return launch()
            except Exception as exc:                # noqa: BLE001
                self.last_error = exc
                kind = classify_failure(exc)
                if kind != TRANSIENT or attempt >= self.policy.max_retries \
                        or (self.retryable is not None
                            and not self.retryable()):
                    raise
                attempt += 1
                self.num_retries += 1
                if self.on_retry is not None:
                    self.on_retry(label, attempt)
                self.policy.sleep(self.policy.delay_s(attempt))
