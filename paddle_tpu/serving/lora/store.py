"""Paged adapter-weight store + registry (ISSUE 15).

S-LoRA's memory insight, mapped onto this tree's own machinery: adapter
weights are just more device pages. `AdapterRegistry` owns a flat
device pool `(num_pages, page_size * 128)` managed by the SAME
`BlockAllocator` discipline the KV cache uses — ref-counted pages, a
FIFO free list, all-or-nothing allocation, page 0 reserved as the
all-zero PAD page — and packs each adapter's padded A/B factors for
every target module into a fixed per-rank-bucket number of pages.

Slot discipline (the determinism backbone): every rank bucket has a
FIXED number of launch slots (slot 0 = the null adapter, all zeros —
the PAD-page idea again). Loading assigns a free slot; unloading frees
it; LRU eviction of IDLE adapters (zero live request refs) makes room.
Compiled programs take the (pool, page-table, scales) arrays as
call-time INPUTS and gather each slot's pages in-graph, so:

* program shapes depend only on the (slots, rank-bucket, page) layout
  — `signature()` rides the ProgramCache key; adapter ids never do,
  and load/unload/evict NEVER recompiles;
* a row's delta reads only its own slot's gathered values, so
  per-adapter outputs are bit-identical between a solo engine and a
  mixed-adapter engine with the same layout (the masked segment-bmm
  adds exact 0.0 for every other slot).

Per-adapter int8 (`load(..., quant="int8")`) stores the payload in a
separate int8 pool (its own allocator — one page discipline each)
through the existing `nn.quant.weight_quantize` path, with per-column
fp32 scales in a dense per-bucket host array; the in-graph gather
dequantizes and the two pools SUM (an adapter lives in exactly one, the
other contributes the PAD page's exact zeros).

Fault points (`utils/faults.py`, table in SERVING.md):
`serving.lora.load_fail` makes `load` raise the typed AdapterLoadError
(mid-stream load failures shed typed, never poison co-batched rows);
`serving.lora.evict_race` makes the LRU evictor ATTEMPT a busy
(live-ref) victim — the refcount guard must refuse it, counted in
`lora_evict_refusals` (a mid-flight request can never lose its
weights).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...utils import faults
from ..kv_cache import BlockAllocator, BlocksExhausted
from .adapter import (AdapterBusy, AdapterLoadError, AdapterNotLoaded,
                      LoRAAdapter)

__all__ = ["LoRALayout", "AdapterRegistry", "llama_lora_dims",
           "FAULT_LOAD", "FAULT_EVICT"]

FAULT_LOAD = faults.register_point("serving.lora.load_fail")
FAULT_EVICT = faults.register_point("serving.lora.evict_race")

LANES = 128          # payload lane width: one allocator "token" = 128 elems
_DEFAULT_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                    "gate_proj", "up_proj", "down_proj")


def llama_lora_dims(cfg, targets=_DEFAULT_TARGETS) -> Dict[str, Tuple[int, int]]:
    """{module: (in_dim, out_dim)} for a Llama-family config — the
    attention q/k/v/o + MLP gate/up/down projections ISSUE 15 targets."""
    h = cfg.hidden_size
    i = cfg.intermediate_size
    hd = h // cfg.num_attention_heads
    kv = cfg.num_key_value_heads * hd
    all_dims = {"q_proj": (h, h), "k_proj": (h, kv), "v_proj": (h, kv),
                "o_proj": (h, h), "gate_proj": (h, i), "up_proj": (h, i),
                "down_proj": (i, h)}
    unknown = [t for t in targets if t not in all_dims]
    if unknown:
        raise ValueError(f"unknown LoRA targets {unknown}")
    return {t: all_dims[t] for t in targets}


class LoRALayout:
    """Static payload geometry: per rank-bucket module offsets into the
    flat paged payload, page counts, and scale-row offsets. Everything
    here is shape-only — it defines program signatures and rides the
    ProgramCache key via `signature()`."""

    def __init__(self, dims: Dict[str, Tuple[int, int]],
                 rank_buckets=(8,), slots: int = 8, page_size: int = 8):
        if slots < 2:
            raise ValueError("need >= 2 slots (slot 0 is the null adapter)")
        self.dims = dict(dims)
        self.targets = tuple(dims)
        self.rank_buckets = tuple(sorted(int(r) for r in rank_buckets))
        if len(set(self.rank_buckets)) != len(self.rank_buckets):
            raise ValueError("duplicate rank buckets")
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.page_elems = self.page_size * LANES
        # per-bucket payload layout: [A_m0 | B_m0 | A_m1 | B_m1 | ...]
        self.offsets: Dict[int, Dict[str, Tuple[int, int]]] = {}
        self.scale_offsets: Dict[int, Dict[str, Tuple[int, int]]] = {}
        self.payload_elems: Dict[int, int] = {}
        self.scale_elems: Dict[int, int] = {}
        self.pages_per_adapter: Dict[int, int] = {}
        for r in self.rank_buckets:
            off, soff = 0, 0
            offs, soffs = {}, {}
            for m, (di, do) in self.dims.items():
                offs[m] = (off, off + di * r)            # A span
                off += di * r
                offs[m + "#B"] = (off, off + r * do)     # B span
                off += r * do
                soffs[m] = (soff, soff + r)              # A scales (r,)
                soff += r
                soffs[m + "#B"] = (soff, soff + do)      # B scales (do,)
                soff += do
            self.offsets[r] = offs
            self.scale_offsets[r] = soffs
            self.payload_elems[r] = off
            self.scale_elems[r] = soff
            tokens = -(-off // LANES)
            self.pages_per_adapter[r] = -(-tokens // self.page_size)

    def bucket_for(self, rank: int) -> int:
        for r in self.rank_buckets:
            if rank <= r:
                return r
        raise AdapterLoadError(
            f"rank {rank} exceeds largest rank bucket "
            f"{self.rank_buckets[-1]}")

    def payload_tokens(self, bucket: int) -> int:
        return -(-self.payload_elems[bucket] // LANES)

    def global_slot(self, bucket: int, local: int) -> int:
        return self.rank_buckets.index(bucket) * self.slots + local

    def signature(self) -> tuple:
        """Static shape identity for ProgramCache keys — adapters load
        and unload without ever changing it."""
        return ("lora", self.slots, self.rank_buckets, self.page_size,
                tuple(sorted((m, d) for m, d in self.dims.items())))


class _Entry:
    __slots__ = ("name", "rank", "bucket", "local", "quant", "seq",
                 "scaling", "refs", "last_use", "gen")

    def __init__(self, name, rank, bucket, local, quant, seq, scaling,
                 gen):
        self.name = name
        self.rank = rank
        self.bucket = bucket
        self.local = local
        self.quant = quant
        self.seq = seq
        self.scaling = float(scaling)
        self.refs = 0
        self.last_use = 0
        # monotonic LOAD generation: the radix-namespace version. A
        # replace/reload under the same NAME gets a new gen, so cached
        # KV donated under the old weights can never match a request
        # served with the new ones (stale-prefix poisoning).
        self.gen = gen


class AdapterRegistry:
    """Runtime adapter store for ONE engine: paged device pools +
    per-bucket slot tables, LRU eviction of idle adapters, live-request
    refcounts. All mutation is host-side bookkeeping plus device
    `.at[pages].set` page writes — never a recompile."""

    def __init__(self, dims: Dict[str, Tuple[int, int]], *,
                 rank_buckets=(8,), slots: int = 8, page_size: int = 8,
                 num_pages: Optional[int] = None,
                 num_quant_pages: Optional[int] = None,
                 counters: Optional[dict] = None):
        import jax.numpy as jnp
        self.layout = LoRALayout(dims, rank_buckets=rank_buckets,
                                 slots=slots, page_size=page_size)
        lay = self.layout
        # default pool sizing: every slot of every bucket can be
        # resident at once (pressure/eviction tests pass smaller pools)
        full = sum((lay.slots - 1) * lay.pages_per_adapter[r]
                   for r in lay.rank_buckets) + 1
        self.num_pages = int(num_pages) if num_pages is not None else full
        self.num_quant_pages = (int(num_quant_pages)
                                if num_quant_pages is not None else full)
        self.allocator = BlockAllocator(self.num_pages, lay.page_size)
        self.quant_allocator = BlockAllocator(self.num_quant_pages,
                                              lay.page_size)
        # page 0 of each pool is the PAD page and stays all-zero: a
        # freed/never-loaded slot's table gathers exact zeros
        self.pool = jnp.zeros((self.num_pages, lay.page_elems),
                              jnp.float32)
        self.quant_pool = jnp.zeros((self.num_quant_pages,
                                     lay.page_elems), jnp.int8)
        # host-side per-bucket launch tables (tiny; jnp-converted per
        # launch by flat_args)
        self._tables_f = {r: np.zeros((lay.slots,
                                       lay.pages_per_adapter[r]),
                                      np.int32)
                          for r in lay.rank_buckets}
        self._tables_q = {r: np.zeros((lay.slots,
                                       lay.pages_per_adapter[r]),
                                      np.int32)
                          for r in lay.rank_buckets}
        self._scales = {r: np.zeros((lay.slots, lay.scale_elems[r]),
                                    np.float32)
                        for r in lay.rank_buckets}
        self._scaling = {r: np.zeros((lay.slots,), np.float32)
                         for r in lay.rank_buckets}
        self._free_slots = {r: list(range(1, lay.slots))
                            for r in lay.rank_buckets}
        self.entries: Dict[str, _Entry] = {}
        self._tick = 0
        self._load_gen = 0
        self.counters = counters if counters is not None else {}

    @classmethod
    def for_model(cls, model, *, targets=_DEFAULT_TARGETS, **kw):
        return cls(llama_lora_dims(model.cfg, targets), **kw)

    # ------------------------------------------------------------ helpers
    def _count(self, key: str, n: int = 1):
        self.counters[key] = self.counters.get(key, 0) + n

    def bind_counters(self, counters: dict):
        """Re-home the registry counters into an engine's metrics
        counters dict (existing counts carry over)."""
        for k, v in self.counters.items():
            counters[k] = counters.get(k, 0) + v
        self.counters = counters

    def _touch(self, entry: _Entry):
        self._tick += 1
        entry.last_use = self._tick

    # ------------------------------------------------------------ queries
    def has(self, name: str) -> bool:
        return name in self.entries

    def adapter_names(self) -> List[str]:
        return sorted(self.entries)

    def slot_of(self, name: str) -> int:
        """Global launch-slot id of a LOADED adapter (0 is the null
        adapter and never names a real one)."""
        e = self.entries.get(name)
        if e is None:
            raise AdapterNotLoaded(f"adapter {name!r} is not loaded",
                                   adapter=name)
        return self.layout.global_slot(e.bucket, e.local)

    def refs_of(self, name: str) -> int:
        e = self.entries.get(name)
        return 0 if e is None else e.refs

    def namespace_of(self, name: str):
        """(name, load-generation) — the radix-cache namespace token
        for requests served under this adapter. The generation changes
        on every (re)load, so prefixes cached under REPLACED weights of
        the same name can never be served again (they age out of the
        tree via LRU)."""
        e = self.entries.get(name)
        if e is None:
            raise AdapterNotLoaded(f"adapter {name!r} is not loaded",
                                   adapter=name)
        return (e.name, e.gen)

    # ------------------------------------------------------------ refs
    def acquire(self, name: str):
        """Pin `name` for one live request: a pinned adapter can never
        be evicted (slot + pages stay put until release)."""
        e = self.entries.get(name)
        if e is None:
            raise AdapterNotLoaded(f"adapter {name!r} is not loaded",
                                   adapter=name)
        e.refs += 1
        self._touch(e)

    def release(self, name: str):
        e = self.entries.get(name)
        if e is None:       # unloaded out from under a ref is a bug
            raise AdapterNotLoaded(f"release of unknown adapter {name!r}",
                                   adapter=name)
        if e.refs <= 0:
            raise RuntimeError(f"double release of adapter {name!r}")
        e.refs -= 1

    # ------------------------------------------------------------ load
    def load(self, adapter: LoRAAdapter, quant: Optional[str] = None):
        """Place `adapter` into a slot + pool pages; returns its global
        slot id. Evicts LRU IDLE adapters on slot/page pressure; raises
        the typed `AdapterLoadError` when nothing evictable remains (or
        the `serving.lora.load_fail` fault fires), `AdapterBusy` never
        — busy adapters are simply not eviction candidates."""
        if faults.fire(FAULT_LOAD) is not None:
            self._count("adapter_load_failures")
            raise AdapterLoadError(
                f"injected load failure for {adapter.name!r}",
                adapter=adapter.name)
        if quant not in (None, "int8"):
            raise ValueError(f"quant must be None or 'int8', got {quant!r}")
        if adapter.name in self.entries:
            self.unload(adapter.name)      # replace (refuses if busy)
        lay = self.layout
        for m, (a, b) in adapter.weights.items():
            if m not in lay.dims:
                raise AdapterLoadError(
                    f"adapter {adapter.name!r} targets {m!r} which is "
                    f"not in the registry layout {lay.targets}",
                    adapter=adapter.name)
            di, do = lay.dims[m]
            if a.shape[0] != di or b.shape[1] != do:
                raise AdapterLoadError(
                    f"adapter {adapter.name!r} module {m!r}: "
                    f"A {a.shape} / B {b.shape} vs layout ({di}, {do})",
                    adapter=adapter.name)
        bucket = lay.bucket_for(adapter.rank)
        if not self._free_slots[bucket] and \
                not self._evict_lru(bucket=bucket):
            self._count("adapter_load_failures")
            raise AdapterLoadError(
                f"no free slot in rank bucket {bucket} and nothing "
                f"idle to evict", adapter=adapter.name)
        alloc = self.quant_allocator if quant == "int8" else self.allocator
        tokens = lay.payload_tokens(bucket)
        while True:
            try:
                seq = alloc.alloc_sequence(tokens)
                break
            except BlocksExhausted:
                if not self._evict_lru(pool=alloc):
                    self._count("adapter_load_failures")
                    raise AdapterLoadError(
                        f"adapter pool exhausted loading "
                        f"{adapter.name!r} ({tokens} tokens needed) and "
                        f"nothing idle to evict", adapter=adapter.name)
        local = self._free_slots[bucket].pop(0)
        payload, scales = self._pack(adapter, bucket, quant)
        self._write_pages(seq.pages, payload, quant)
        table = self._tables_q if quant == "int8" else self._tables_f
        table[bucket][local, :len(seq.pages)] = seq.pages
        self._scales[bucket][local] = scales
        self._scaling[bucket][local] = adapter.scaling
        self._load_gen += 1
        entry = _Entry(adapter.name, adapter.rank, bucket, local, quant,
                       seq, adapter.scaling, self._load_gen)
        self.entries[adapter.name] = entry
        self._touch(entry)
        self._count("adapters_loaded")
        return lay.global_slot(bucket, local)

    def _pack(self, adapter: LoRAAdapter, bucket: int,
              quant: Optional[str]):
        """Flat payload (pages * page_elems,) + dense scale row for one
        adapter: A/B padded to the bucket rank (zero columns/rows — an
        exact no-op on the delta), int8 quantized per out-channel via
        the existing nn.quant path."""
        lay = self.layout
        r = bucket
        n_pages = lay.pages_per_adapter[r]
        dtype = np.int8 if quant == "int8" else np.float32
        payload = np.zeros((n_pages * lay.page_elems,), dtype)
        scales = np.zeros((lay.scale_elems[r],), np.float32)
        for m, (di, do) in lay.dims.items():
            got = adapter.weights.get(m)
            if got is None:
                continue                  # module not targeted: zeros
            a, b = got
            ap = np.zeros((di, r), np.float32)
            ap[:, :adapter.rank] = a
            bp = np.zeros((r, do), np.float32)
            bp[:adapter.rank, :] = b
            if quant == "int8":
                aq, asc = _quantize_int8(ap)
                bq, bsc = _quantize_int8(bp)
                o0, o1 = lay.offsets[r][m]
                payload[o0:o1] = aq.ravel()
                o0, o1 = lay.offsets[r][m + "#B"]
                payload[o0:o1] = bq.ravel()
                s0, s1 = lay.scale_offsets[r][m]
                scales[s0:s1] = asc
                s0, s1 = lay.scale_offsets[r][m + "#B"]
                scales[s0:s1] = bsc
            else:
                o0, o1 = lay.offsets[r][m]
                payload[o0:o1] = ap.ravel()
                o0, o1 = lay.offsets[r][m + "#B"]
                payload[o0:o1] = bp.ravel()
        return payload, scales

    def _write_pages(self, pages: List[int], payload: np.ndarray,
                     quant: Optional[str]):
        import jax.numpy as jnp
        lay = self.layout
        chunks = payload.reshape(len(pages), lay.page_elems)
        idx = jnp.asarray(np.asarray(pages, np.int32))
        if quant == "int8":
            self.quant_pool = self.quant_pool.at[idx].set(
                jnp.asarray(chunks))
        else:
            self.pool = self.pool.at[idx].set(jnp.asarray(chunks))

    # ------------------------------------------------------------ unload
    def unload(self, name: str):
        """Explicit unload; refuses (typed AdapterBusy) while live
        requests still pin the adapter."""
        e = self.entries.get(name)
        if e is None:
            raise AdapterNotLoaded(f"adapter {name!r} is not loaded",
                                   adapter=name)
        if e.refs > 0:
            raise AdapterBusy(
                f"adapter {name!r} has {e.refs} live request refs",
                adapter=name, refs=e.refs)
        self._drop(e)
        self._count("adapters_unloaded")

    def _drop(self, e: _Entry):
        alloc = self.quant_allocator if e.quant == "int8" \
            else self.allocator
        alloc.free_sequence(e.seq)
        table = self._tables_q if e.quant == "int8" else self._tables_f
        table[e.bucket][e.local, :] = 0        # gather the PAD page
        self._scales[e.bucket][e.local, :] = 0.0
        self._scaling[e.bucket][e.local] = 0.0
        self._free_slots[e.bucket].append(e.local)
        self._free_slots[e.bucket].sort()
        del self.entries[e.name]

    def _evict_lru(self, bucket: Optional[int] = None, pool=None) -> bool:
        """Evict ONE least-recently-used IDLE adapter (optionally
        restricted to a bucket or a pool's allocator). The
        `serving.lora.evict_race` fault makes this attempt a BUSY
        victim first — the refcount guard refuses it (counted), which
        is the whole point of the guard."""
        if faults.fire(FAULT_EVICT) is not None:
            busy = [e for e in self.entries.values() if e.refs > 0]
            if busy:
                self._count("lora_evict_refusals")
        cands = [e for e in self.entries.values() if e.refs == 0]
        if bucket is not None:
            cands = [e for e in cands if e.bucket == bucket]
        if pool is not None:
            want_q = pool is self.quant_allocator
            cands = [e for e in cands if (e.quant == "int8") == want_q]
        if not cands:
            return False
        victim = min(cands, key=lambda e: e.last_use)
        self._drop(victim)
        self._count("adapters_evicted")
        return True

    # ------------------------------------------------------------ launch
    def flat_args(self) -> tuple:
        """The launch-input tuple every lora-enabled program takes:
        (pool_f32, pool_int8) + per rank bucket
        (table_f32, table_int8, scales, scaling). Pools are live device
        arrays; the per-bucket tables are tiny host arrays converted
        here. Shapes are layout-static — only VALUES change across
        load/unload, so the ProgramCache key never moves."""
        import jax.numpy as jnp
        out = [self.pool, self.quant_pool]
        for r in self.layout.rank_buckets:
            out.extend([jnp.asarray(self._tables_f[r]),
                        jnp.asarray(self._tables_q[r]),
                        jnp.asarray(self._scales[r]),
                        jnp.asarray(self._scaling[r])])
        return tuple(out)

    def signature(self) -> tuple:
        return self.layout.signature() + (self.num_pages,
                                          self.num_quant_pages)

    def check_invariants(self):
        self.allocator.check_invariants()
        self.quant_allocator.check_invariants()
        for e in self.entries.values():
            assert e.refs >= 0
            assert e.local not in self._free_slots[e.bucket]


def _quantize_int8(w: np.ndarray):
    """(in, out) fp32 -> (int8, per-out-channel fp32 scale), the same
    math as nn.quant.weight_quantize('weight_only_int8') — kept in
    numpy so packing a payload never touches the dispatch/AMP stack."""
    absmax = np.maximum(np.abs(w).max(axis=0), 1e-10)
    scale = (absmax / 127.0).astype(np.float32)
    q = np.clip(np.round(w / scale[None, :]), -127, 127).astype(np.int8)
    return q, scale
