"""paddle_tpu.serving.lora — multi-LoRA adapter serving (ISSUE 15).

Serve N fine-tuned variants of one base model in a single
ServingEngine, S-LoRA/Punica style: adapter weights live PAGED in a
device pool managed with the BlockAllocator's refcount/free-list
discipline (`store.AdapterRegistry`), every compiled program gathers
the loaded adapters' A/B pages in-graph into fixed-shape per-rank-
bucket slot stacks, and one batched heterogeneous segment matmul
(`kernels/lora_matmul.py`) applies each row's OWN adapter delta —
rows of one launch may carry different adapters, and the program grid
never grows per adapter (the stack/slot geometry rides the program
key, individual adapter ids never do).

The runtime half (`runtime.py`) threads the launch's adapter context
through the model's projection hooks via a trace-time scope — zero
cost when no scope is active (the training path and lora-less engines
trace exactly the graphs they always did).
"""
from .adapter import (AdapterBusy, AdapterError, AdapterLoadError,
                      AdapterNotLoaded, LoRAAdapter)
from .store import AdapterRegistry, LoRALayout
from .runtime import lora_scope, current_lora, apply_lora

__all__ = ["LoRAAdapter", "AdapterRegistry", "LoRALayout",
           "AdapterError", "AdapterNotLoaded", "AdapterLoadError",
           "AdapterBusy", "lora_scope", "current_lora", "apply_lora"]
