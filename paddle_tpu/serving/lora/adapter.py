"""LoRA adapter objects + the typed error family (ISSUE 15).

An adapter is host-side data: per-target-module (A, B) low-rank
factors plus the alpha/rank scaling. Device placement, paging and slot
assignment all belong to `store.AdapterRegistry` — an adapter object
can be loaded into any registry whose layout its shapes fit.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["LoRAAdapter", "AdapterError", "AdapterNotLoaded",
           "AdapterLoadError", "AdapterBusy"]


class AdapterError(RuntimeError):
    """Base of the typed adapter failures (all carry .adapter)."""

    def __init__(self, msg, adapter: Optional[str] = None, **kw):
        super().__init__(msg)
        self.adapter = adapter
        for k, v in kw.items():
            setattr(self, k, v)


class AdapterNotLoaded(AdapterError):
    """A request (or snapshot adoption) named an adapter this engine's
    registry does not currently hold — shed typed at the door, never
    served with the wrong (or no) adapter."""


class AdapterLoadError(AdapterError):
    """Loading failed: pool exhausted with nothing evictable, shape
    mismatch against the registry layout, or the injected
    `serving.lora.load_fail` fault."""


class AdapterBusy(AdapterError):
    """Unload/evict refused: the adapter still has live request refs.
    Eviction only ever takes idle adapters — a mid-flight request can
    never lose its weights under it."""


class LoRAAdapter:
    """One named adapter: {module: (A (in, r), B (r, out))} fp32
    ndarrays + LoRA scaling alpha/r (applied once per delta)."""

    def __init__(self, name: str, rank: int,
                 weights: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 alpha: Optional[float] = None):
        self.name = str(name)
        self.rank = int(rank)
        if self.rank < 1:
            raise ValueError("rank must be >= 1")
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.weights = {}
        for mod, (a, b) in weights.items():
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            if a.ndim != 2 or b.ndim != 2 or a.shape[1] != self.rank \
                    or b.shape[0] != self.rank:
                raise ValueError(
                    f"adapter {name!r} module {mod!r}: A {a.shape} / "
                    f"B {b.shape} do not factor through rank {rank}")
            self.weights[mod] = (a, b)
        if not self.weights:
            raise ValueError("adapter has no target modules")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank

    @classmethod
    def random(cls, name: str, rank: int, dims: Dict[str, Tuple[int, int]],
               seed: int = 0, scale: float = 0.02,
               alpha: Optional[float] = None) -> "LoRAAdapter":
        """Test/bench helper: gaussian A, gaussian B (B deliberately
        NON-zero so the delta is visible — a fresh-trained adapter
        would have B=0 and be indistinguishable from the base)."""
        rng = np.random.RandomState(seed)
        w = {m: (rng.randn(di, rank).astype(np.float32) * scale,
                 rng.randn(rank, do).astype(np.float32) * scale)
             for m, (di, do) in dims.items()}
        return cls(name, rank, w, alpha=alpha)

    def __repr__(self):
        return (f"LoRAAdapter({self.name!r}, r={self.rank}, "
                f"modules={sorted(self.weights)})")
