"""Trace-time LoRA threading: scope, in-graph paged gather, delta op.

The engine's lora-enabled programs take the registry's `flat_args()`
(pools, page tables, scales) plus the launch's per-row slot ids as
ordinary jit arguments, build a `LoRAContext` from them INSIDE the
traced program body, and enter `lora_scope(ctx)` around the model
call. The model's projection hooks (`apply_lora`, called from
models/llama.py) read the ambient scope: with none active they return
the projection output UNTOUCHED — the training path and lora-less
engines trace exactly the graphs they always did, at the cost of one
thread-local read per projection per trace.

In-graph gather (the paged read path): for each rank bucket, the slot
stacks A (S, H, R) / B (S, R, N) materialize from the pools via
`pool[page_table]` — the same gathered-view idea the chunk program
uses for the paged KV prefix. Quantized slots dequantize during the
gather (per-column scales) and the two pools SUM: an adapter lives in
exactly one pool while the other's table rows hold the all-zero PAD
page, so the sum adds an exact 0.0 and bit-identity across
fp32/int8/mixed layouts of OTHER slots holds by construction. Per-slot
alpha/rank scaling folds into the B stack once, here, so the Pallas
kernel and the XLA fallback compute the identical x @ A @ (B*scale).

Delta dispatch: single-token rows (decode, multi-decode scan steps) go
through the masked segment-bmm Pallas kernel when the tiling is legal
(`kernels/lora_matmul.py`); multi-token rows (prefill chunks) and
untileable shapes take the XLA gathered-bmv. Rows whose slot falls
outside a bucket map to that bucket's null slot 0 (all zeros), so the
per-bucket sum needs no extra masking.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np

from ...ops.dispatch import apply_op

__all__ = ["lora_scope", "current_lora", "apply_lora", "LoRAContext",
           "build_context"]

_ACTIVE = threading.local()


def current_lora():
    """The active LoRAContext, or None (the one check the default
    trace path pays)."""
    return getattr(_ACTIVE, "ctx", None)


@contextmanager
def lora_scope(ctx):
    prev = getattr(_ACTIVE, "ctx", None)
    _ACTIVE.ctx = ctx
    try:
        yield ctx
    finally:
        _ACTIVE.ctx = prev


class LoRAContext:
    """One launch's adapter view: per-bucket per-module (A, B) stacks
    (B pre-scaled) + the per-row global slot ids."""

    def __init__(self, layout, stacks, row_slots):
        self.layout = layout
        self.stacks = stacks          # {bucket: {module: (A, B)}}
        self.row_slots = row_slots    # (B,) int32 global slot ids

    def delta(self, module, x):
        """(b, t, h) x -> (b, t, out) fp32 delta, summed over the rank
        buckets (a row lives in exactly one; others hit null slot 0)."""
        import jax.numpy as jnp
        from ...kernels.lora_matmul import (lora_matmul,
                                            lora_matmul_supported,
                                            lora_matmul_xla)
        lay = self.layout
        b, t, h = x.shape
        x2 = x.reshape(b * t, h)
        slots = self.row_slots.astype(jnp.int32)
        if t > 1:
            slots = jnp.repeat(slots, t)
        total = None
        for bi, r in enumerate(lay.rank_buckets):
            a_stack, b_stack = self.stacks[r][module]
            local = slots - np.int32(bi * lay.slots)
            in_bucket = jnp.logical_and(local >= 0, local < lay.slots)
            local = jnp.where(in_bucket, local, 0)
            n_out = b_stack.shape[2]
            if t == 1 and lora_matmul_supported(b, h, r, n_out, x2.dtype):
                d = lora_matmul(x2, local, a_stack, b_stack)
            else:
                d = lora_matmul_xla(x2, local, a_stack, b_stack)
            total = d if total is None else total + d
        return total.reshape(b, t, -1)


def build_context(layout, flat_args, row_slots):
    """Unflatten a registry `flat_args()` tuple (traced) + per-row slot
    ids into a LoRAContext: gather every bucket's slot payloads from
    the paged pools, slice/reshape per module, dequantize int8 slots,
    fold the per-slot scaling into B."""
    import jax.numpy as jnp
    pool_f, pool_q = flat_args[0], flat_args[1]
    stacks = {}
    idx = 2
    for r in layout.rank_buckets:
        table_f, table_q, scales, scaling = flat_args[idx:idx + 4]
        idx += 4
        # (slots, pages, page_elems) -> (slots, pages * page_elems):
        # the flat payload view _pack wrote, PAD rows exact zeros
        pay_f = jnp.take(pool_f, table_f, axis=0).reshape(
            layout.slots, -1)
        pay_q = jnp.take(pool_q, table_q, axis=0).reshape(
            layout.slots, -1)
        per_mod = {}
        for m, (di, do) in layout.dims.items():
            a0, a1 = layout.offsets[r][m]
            b0, b1 = layout.offsets[r][m + "#B"]
            s0, s1 = layout.scale_offsets[r][m]
            t0, t1 = layout.scale_offsets[r][m + "#B"]
            a_f = pay_f[:, a0:a1].reshape(layout.slots, di, r)
            b_f = pay_f[:, b0:b1].reshape(layout.slots, r, do)
            a_q = pay_q[:, a0:a1].reshape(
                layout.slots, di, r).astype(jnp.float32) \
                * scales[:, s0:s1][:, None, :]
            b_q = pay_q[:, b0:b1].reshape(
                layout.slots, r, do).astype(jnp.float32) \
                * scales[:, t0:t1][:, None, :]
            a = a_f + a_q                       # one pool is exact zeros
            bmat = (b_f + b_q) * scaling[:, None, None]
            per_mod[m] = (a, bmat)
        stacks[r] = per_mod
    return LoRAContext(layout, stacks, row_slots)


def apply_lora(module: str, x, y):
    """Projection hook (called from models/llama.py): y + delta when a
    scope is active and targets `module`; y itself otherwise. x is the
    projection INPUT, y its output (Tensors)."""
    ctx = current_lora()
    if ctx is None or module not in ctx.layout.dims:
        return y

    def _add(xa, ya):
        return ya + ctx.delta(module, xa).astype(ya.dtype)

    return apply_op("lora_delta", _add, x, y)
