"""Rule A4 — runtime-safety hazards: interpret=True shipping in
non-test code, and device-side loops long enough to wedge the chip.

Chip history: interpret=True on CPU hides every Mosaic legality issue
(round-1 lesson — all kernels route through `_interpret_mode()`, which
is False on real TPU, never a literal True); and a 4096-iteration
device-side Mosaic loop wedged the device UNAVAILABLE for minutes,
which is why kernels/timing.py caps its fori_loop chains at 512
iterations.
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule

WEDGE_CAP = 512  # kernels/timing.py loop_cap — the measured safe bound


def _calls(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func) or ""
            yield n, name.split(".")[-1]


@register_rule(
    "A4", ("interpret", "timing-cap"), Severity.ERROR,
    "interpret=True in non-test code / device loops over the 512-iter "
    "wedge cap")
def check_runtime_safety(ctx):
    out = []
    for call, leaf in _calls(ctx.tree):
        if leaf == "pallas_call" and not ctx.is_test:
            for kw in call.keywords:
                if kw.arg == "interpret" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    out.append(Diagnostic(
                        rule="A4", slug="interpret", severity=Severity.ERROR,
                        path=ctx.path, line=kw.value.lineno,
                        col=kw.value.col_offset,
                        message="interpret=True hardcoded in non-test "
                                "code: the kernel would run the Pallas "
                                "interpreter on real TPU too, and "
                                "interpret mode hides every Mosaic "
                                "legality violation",
                        hint="route through a backend probe like "
                             "kernels.flash_attention._interpret_mode()"))
        elif leaf == "device_time":
            for arg_kw in ("loop_cap", "iters"):
                node = astutil.get_arg(call, None, arg_kw)
                val = astutil.resolve_int(node, ctx.consts) \
                    if node is not None else None
                if val is not None and val > WEDGE_CAP:
                    out.append(Diagnostic(
                        rule="A4", slug="timing-cap", severity=Severity.ERROR,
                        path=ctx.path, line=node.lineno, col=node.col_offset,
                        message=(f"device_time {arg_kw}={val} exceeds the "
                                 f"{WEDGE_CAP}-iteration wedge cap: a "
                                 "4096-iteration device-side Mosaic loop "
                                 "left the chip UNAVAILABLE for minutes"),
                        hint=f"stay at or under {WEDGE_CAP}; device_time "
                             "differences N vs 2N loops, so long loops "
                             "buy no accuracy"))
        elif leaf == "fori_loop":
            lo = astutil.get_arg(call, 0, "lower")
            hi = astutil.get_arg(call, 1, "upper")
            lo_v = astutil.resolve_int(lo, ctx.consts) if lo is not None \
                else None
            hi_v = astutil.resolve_int(hi, ctx.consts) if hi is not None \
                else None
            if lo_v is not None and hi_v is not None \
                    and hi_v - lo_v > WEDGE_CAP:
                out.append(Diagnostic(
                    rule="A4", slug="timing-cap", severity=Severity.ERROR,
                    path=ctx.path, line=call.lineno, col=call.col_offset,
                    message=(f"fori_loop with a static {hi_v - lo_v}"
                             "-iteration trip count: device-side loops "
                             f"past ~{WEDGE_CAP} iterations have wedged "
                             "the chip (UNAVAILABLE) over this transport"),
                    hint="chunk the loop or derive the bound from data "
                         "shapes; annotate `# tpu-lint: timing-cap-ok` "
                         "if this cannot run device-side"))
    return out
