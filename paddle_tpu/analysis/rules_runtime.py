"""Rule A4 — runtime-safety hazards: interpret=True shipping in
non-test code, and device-side loops long enough to wedge the chip.

Chip history: interpret=True on CPU hides every Mosaic legality issue
(round-1 lesson — all kernels route through `_interpret_mode()`, which
is False on real TPU, never a literal True); and a 4096-iteration
device-side Mosaic loop wedged the device UNAVAILABLE for minutes,
which is why kernels/timing.py caps its fori_loop chains at 512
iterations.
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule

WEDGE_CAP = 512  # kernels/timing.py loop_cap — the measured safe bound


def _calls(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func) or ""
            yield n, name.split(".")[-1]


def _resolve_bound(node, consts):
    """Trip-count resolution for device-side loops: `resolve_int` plus
    `min(...)` — a min over any resolvable operand is bounded by the
    smallest of them, which is how the serving multi-decode loop
    (ISSUE 13) makes its data-driven K lint-provably bounded:
    `jnp.arange(min(int(k_steps), 512))` resolves to 512 even though
    k_steps itself is a runtime value.

    SOUND ONLY FOR UPPER endpoints (upper / length / arange stop): a
    min() resolves to an upper BOUND on the runtime value. A loop's
    LOWER endpoint must use plain resolve_int — an upper bound on `lo`
    UNDERestimates the hi - lo trip count."""
    if isinstance(node, ast.Call):
        fname = (astutil.dotted_name(node.func) or "").split(".")[-1]
        if fname == "min" and node.args and not node.keywords:
            vals = [_resolve_bound(a, consts) for a in node.args]
            vals = [v for v in vals if v is not None]
            return min(vals) if vals else None
    return astutil.resolve_int(node, consts)


def _scan_trip(call, consts):
    """Static trip count of a lax.scan call, when resolvable: the
    `length=` kwarg, or an `arange(...)`-built xs (positional arg 2 or
    the xs kwarg). None when data-driven/unresolvable — rules must
    skip, not guess (package scans legitimately run data-length loops
    under XLA; the wedge class is the STATICALLY-huge trip count)."""
    length = astutil.get_arg(call, None, "length")
    if length is not None:
        return _resolve_bound(length, consts)
    xs = astutil.get_arg(call, 2, "xs")
    if isinstance(xs, ast.Call):
        leaf = (astutil.dotted_name(xs.func) or "").split(".")[-1]
        if leaf == "arange":
            if len(xs.args) == 1:
                return _resolve_bound(xs.args[0], consts)
            if len(xs.args) == 2:
                # lower endpoint: exact values only (resolve_int) — a
                # min()-clamped lo would UNDERestimate hi - lo
                lo = astutil.resolve_int(xs.args[0], consts)
                hi = _resolve_bound(xs.args[1], consts)
                if lo is not None and hi is not None:
                    return hi - lo
    return None


@register_rule(
    "A4", ("interpret", "timing-cap"), Severity.ERROR,
    "interpret=True in non-test code / device loops over the 512-iter "
    "wedge cap")
def check_runtime_safety(ctx):
    out = []
    for call, leaf in _calls(ctx.tree):
        if leaf == "pallas_call" and not ctx.is_test:
            for kw in call.keywords:
                if kw.arg == "interpret" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    out.append(Diagnostic(
                        rule="A4", slug="interpret", severity=Severity.ERROR,
                        path=ctx.path, line=kw.value.lineno,
                        col=kw.value.col_offset,
                        message="interpret=True hardcoded in non-test "
                                "code: the kernel would run the Pallas "
                                "interpreter on real TPU too, and "
                                "interpret mode hides every Mosaic "
                                "legality violation",
                        hint="route through a backend probe like "
                             "kernels.flash_attention._interpret_mode()"))
        elif leaf == "device_time":
            for arg_kw in ("loop_cap", "iters"):
                node = astutil.get_arg(call, None, arg_kw)
                val = astutil.resolve_int(node, ctx.consts) \
                    if node is not None else None
                if val is not None and val > WEDGE_CAP:
                    out.append(Diagnostic(
                        rule="A4", slug="timing-cap", severity=Severity.ERROR,
                        path=ctx.path, line=node.lineno, col=node.col_offset,
                        message=(f"device_time {arg_kw}={val} exceeds the "
                                 f"{WEDGE_CAP}-iteration wedge cap: a "
                                 "4096-iteration device-side Mosaic loop "
                                 "left the chip UNAVAILABLE for minutes"),
                        hint=f"stay at or under {WEDGE_CAP}; device_time "
                             "differences N vs 2N loops, so long loops "
                             "buy no accuracy"))
        elif leaf == "fori_loop":
            lo = astutil.get_arg(call, 0, "lower")
            hi = astutil.get_arg(call, 1, "upper")
            # lower endpoint: exact only — min()-clamp resolution is an
            # upper bound, sound for `upper` but not for `lower`
            lo_v = astutil.resolve_int(lo, ctx.consts) if lo is not None \
                else None
            hi_v = _resolve_bound(hi, ctx.consts) if hi is not None \
                else None
            if lo_v is not None and hi_v is not None \
                    and hi_v - lo_v > WEDGE_CAP:
                out.append(Diagnostic(
                    rule="A4", slug="timing-cap", severity=Severity.ERROR,
                    path=ctx.path, line=call.lineno, col=call.col_offset,
                    message=(f"fori_loop with a static {hi_v - lo_v}"
                             "-iteration trip count: device-side loops "
                             f"past ~{WEDGE_CAP} iterations have wedged "
                             "the chip (UNAVAILABLE) over this transport"),
                    hint="chunk the loop, derive the bound from data "
                         "shapes, or clamp it provably (min(n, "
                         f"{WEDGE_CAP}) — the multi-decode idiom); "
                         "annotate `# tpu-lint: timing-cap-ok` "
                         "if this cannot run device-side"))
        elif leaf == "scan":
            # the multi-step decode loop (ISSUE 13) is a lax.scan over
            # the decode body: a bounded trip (K clamped by
            # min(k, <=512) or a small static arange/length) passes; a
            # STATICALLY oversized or uselessly-clamped one is the same
            # wedge class as the fori_loop above. Data-driven lengths
            # stay un-flagged — XLA scans over sequence lengths are
            # normal; the hazard is the provably huge trip count.
            trip = _scan_trip(call, ctx.consts)
            if trip is not None and trip > WEDGE_CAP:
                out.append(Diagnostic(
                    rule="A4", slug="timing-cap", severity=Severity.ERROR,
                    path=ctx.path, line=call.lineno, col=call.col_offset,
                    message=(f"lax.scan with a static {trip}-iteration "
                             "trip count: device-side loops past "
                             f"~{WEDGE_CAP} iterations have wedged the "
                             "chip (UNAVAILABLE) over this transport"),
                    hint="chunk the loop or clamp the trip count "
                         f"provably (min(k, {WEDGE_CAP}) — the "
                         "multi-decode idiom); annotate "
                         "`# tpu-lint: timing-cap-ok` if this cannot "
                         "run device-side"))
    return out
