"""Rule registry for tpu-lint.

A rule is a callable `check(ctx) -> iterable[Diagnostic]` registered
with an id (A1..A5), a set of slugs it may emit (the escape-hatch
tokens), a default severity and a one-line summary. The drivers in
driver.py run every selected rule over a parsed FileContext.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Tuple

__all__ = ["Rule", "register_rule", "all_rules", "select_rules"]


@dataclass(frozen=True)
class Rule:
    id: str
    slugs: Tuple[str, ...]
    severity: str
    summary: str
    check: Callable = field(compare=False)


_RULES: dict = {}


def register_rule(id, slugs, severity, summary):
    """Decorator: register `check(ctx)` under rule `id`."""
    def deco(fn):
        if id in _RULES:
            raise ValueError(f"duplicate rule id {id}")
        _RULES[id] = Rule(id=id, slugs=tuple(slugs), severity=severity,
                          summary=summary, check=fn)
        return fn
    return deco


def all_rules():
    return [_RULES[k] for k in sorted(_RULES)]


def _matches(rule, tok):
    """One selector against one rule: exact id/slug match, or a
    trailing-`*` prefix glob over rule IDS only (`B*` selects the
    whole B pack; slugs are excluded from globbing so `B*` cannot
    surprise-match the A2 slug "blockspec")."""
    if tok.endswith("*"):
        return rule.id.lower().startswith(tok[:-1])
    return rule.id.lower() == tok \
        or any(s.lower() == tok for s in rule.slugs)


def select_rules(tokens=None):
    """Rules whose id OR one of whose slugs matches any token
    (case-insensitive; a trailing `*` prefix-globs, so `--rules B*`
    selects a whole pack). tokens=None selects everything."""
    rules = all_rules()
    if not tokens:
        return rules
    toks = {t.strip().lower() for t in tokens if t.strip()}
    if not toks:
        # "--rules ," / "--rules ''" must not select NOTHING and pass
        # vacuously — an empty selection is a usage error
        raise ValueError("empty rule selection (no ids/slugs given)")
    out = [r for r in rules if any(_matches(r, t) for t in toks)]
    unknown = {t for t in toks
               if not any(_matches(r, t) for r in rules)}
    if unknown:
        raise ValueError(f"unknown rule selector(s): {sorted(unknown)}; "
                         f"known: {[r.id for r in rules]} + slugs "
                         f"(+ prefix globs like B*)")
    return out
