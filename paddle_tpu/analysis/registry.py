"""Rule registry for tpu-lint.

A rule is a callable `check(ctx) -> iterable[Diagnostic]` registered
with an id (A1..A5), a set of slugs it may emit (the escape-hatch
tokens), a default severity and a one-line summary. The drivers in
driver.py run every selected rule over a parsed FileContext.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Tuple

__all__ = ["Rule", "register_rule", "all_rules", "select_rules"]


@dataclass(frozen=True)
class Rule:
    id: str
    slugs: Tuple[str, ...]
    severity: str
    summary: str
    check: Callable = field(compare=False)


_RULES: dict = {}


def register_rule(id, slugs, severity, summary):
    """Decorator: register `check(ctx)` under rule `id`."""
    def deco(fn):
        if id in _RULES:
            raise ValueError(f"duplicate rule id {id}")
        _RULES[id] = Rule(id=id, slugs=tuple(slugs), severity=severity,
                          summary=summary, check=fn)
        return fn
    return deco


def all_rules():
    return [_RULES[k] for k in sorted(_RULES)]


def select_rules(tokens=None):
    """Rules whose id OR one of whose slugs matches any token
    (case-insensitive). tokens=None selects everything."""
    rules = all_rules()
    if not tokens:
        return rules
    toks = {t.strip().lower() for t in tokens if t.strip()}
    if not toks:
        # "--rules ," / "--rules ''" must not select NOTHING and pass
        # vacuously — an empty selection is a usage error
        raise ValueError("empty rule selection (no ids/slugs given)")
    out = []
    for r in rules:
        if r.id.lower() in toks or any(s.lower() in toks for s in r.slugs):
            out.append(r)
    unknown = toks - {r.id.lower() for r in rules} \
        - {s.lower() for r in rules for s in r.slugs}
    if unknown:
        raise ValueError(f"unknown rule selector(s): {sorted(unknown)}; "
                         f"known: {[r.id for r in rules]} + slugs")
    return out
