"""Rules A2 + A3 — BlockSpec tiling legality and VMEM budgeting.

A2 replays Mosaic's `_check_block_mappings` rule statically: the last
two dims of a block shape must be divisible by (8, 128) respectively —
or equal the corresponding ARRAY dims, which a linter cannot see, hence
the `# tpu-lint: blockspec-ok` escape hatch for that case. The lse
(1, block_q) out-spec crash of round 1 and the legality sweeps in
tests/test_flash_blockspec_legality.py are the chip history here.

A3 runs the vmem.py estimator over every pallas_call whose block
shapes, out dtype and scratch shapes all resolve statically; the rms
`block_rows=256 @ H=4096` fp32 pick that OOM'd on chip ("scoped vmem
24.2M > 16M") is the motivating catch. Anything unresolvable is
skipped — the rule never guesses shapes.
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule
from .vmem import VMEM_BUDGET_BYTES, DTYPE_BYTES, fits_vmem

_MB = 1024.0 * 1024.0


def _calls_named(tree, leaf):
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func) or ""
            if name.split(".")[-1] == leaf:
                yield n


# ------------------------------------------------------------------- A2
@register_rule(
    "A2", ("blockspec",), Severity.ERROR,
    "BlockSpec last-two block dims must be (8, 128)-divisible")
def check_blockspec_divisibility(ctx):
    out = []
    for call in _calls_named(ctx.tree, "BlockSpec"):
        shape_node = astutil.get_arg(call, 0, "block_shape")
        if not isinstance(shape_node, (ast.Tuple, ast.List)) \
                or not shape_node.elts:
            continue
        elts = shape_node.elts
        # check only when the trailing dims all resolve — a partially
        # literal shape says nothing about legality
        tail = elts[-2:] if len(elts) >= 2 else elts[-1:]
        dims = [astutil.resolve_int(e, ctx.consts) for e in tail]
        if any(d is None for d in dims):
            continue
        checks = []
        if len(dims) == 2:
            checks = [(tail[0], dims[0], 8, "second-to-last"),
                      (tail[1], dims[1], 128, "last")]
        else:
            checks = [(tail[0], dims[0], 128, "last")]
        for node, val, div, which in checks:
            if val % div != 0:
                out.append(Diagnostic(
                    rule="A2", slug="blockspec", severity=Severity.ERROR,
                    path=ctx.path, line=node.lineno, col=node.col_offset,
                    message=(f"{which} block dim {val} is not divisible "
                             f"by {div}: Mosaic rejects this tiling "
                             "unless the block dim equals the array dim "
                             "(interpret=True hides it; round-1 lse-spec "
                             "chip crash)"),
                    hint="pick an (8, 128)-divisible block, or — if the "
                         "block spans the whole array dim — annotate the "
                         "line with `# tpu-lint: blockspec-ok`"))
    return out


# ------------------------------------------------------------------- A3
def _spec_shapes(node, ctx):
    """Resolve a single BlockSpec-call node to a block shape tuple.
    Returns None when unresolvable."""
    if not isinstance(node, ast.Call):
        return None
    name = astutil.dotted_name(node.func) or ""
    if name.split(".")[-1] != "BlockSpec":
        return None
    shape_node = astutil.get_arg(node, 0, "block_shape")
    if shape_node is None:
        return None
    return astutil.resolve_shape(shape_node, ctx.consts)


def _spec_list(node, ctx):
    """[(shape, ...)] for an in_specs/out_specs node: a single BlockSpec
    or a plain list of them. None when any entry is unresolvable."""
    if node is None:
        return []
    items = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    shapes = []
    for it in items:
        s = _spec_shapes(it, ctx)
        if s is None:
            return None
        shapes.append(s)
    return shapes


def _out_dtype(call, ctx):
    """dtype string from out_shape=jax.ShapeDtypeStruct(shape, dtype);
    float32 (the conservative worst case) when unresolvable."""
    node = astutil.get_arg(call, None, "out_shape")
    if node is None:
        return "float32"
    cands = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for c in cands:
        if isinstance(c, ast.Call):
            dt = astutil.get_arg(c, 1, "dtype")
            name = astutil.dtype_name(dt) if dt is not None else None
            if name in DTYPE_BYTES:
                return name
    return "float32"


def _scratch_blocks(call, ctx):
    """[(shape, dtype)] for scratch_shapes=[pltpu.VMEM(shape, dtype),
    ...]. None when present but unresolvable; [] when absent."""
    node = astutil.get_arg(call, None, "scratch_shapes")
    if node is None:
        return []
    items = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    blocks = []
    for it in items:
        if not isinstance(it, ast.Call):
            return None
        shape = astutil.resolve_shape(astutil.get_arg(it, 0, "shape"),
                                      ctx.consts)
        if shape is None:
            return None
        dt_node = astutil.get_arg(it, 1, "dtype")
        dt = astutil.dtype_name(dt_node) if dt_node is not None else None
        blocks.append((shape, dt if dt in DTYPE_BYTES else "float32"))
    return blocks


def _in_dtypes(call, ctx, n):
    """Per-in-spec dtypes from a `# tpu-lint-hint: vmem-dtypes=a,b,...`
    comment anywhere inside the pallas_call's span — the quantized-
    kernel refinement (ISSUE 6): int8/int4 weight blocks and fp32
    scale buffers are budgeted at their TRUE widths instead of the out
    dtype's. Ignored (conservative out-dtype path) when the list
    doesn't match the spec count or names an unknown dtype."""
    hint = getattr(ctx, "hint_for", lambda *_: None)(call, "vmem-dtypes")
    if not hint:
        return None
    names = [t.strip().lower() for t in hint.split(",")]
    if len(names) != n or not all(t in DTYPE_BYTES for t in names):
        return None
    return names


@register_rule(
    "A3", ("vmem",), Severity.ERROR,
    "pallas_call block picks must fit the ~16 MB scoped-VMEM budget")
def check_vmem_budget(ctx):
    out = []
    for call in _calls_named(ctx.tree, "pallas_call"):
        spec_src = call
        gs = astutil.get_arg(call, None, "grid_spec")
        if isinstance(gs, ast.Call):
            spec_src = gs  # PrefetchScalarGridSpec carries the specs
        in_shapes = _spec_list(
            astutil.get_arg(spec_src, None, "in_specs"), ctx)
        out_shapes = _spec_list(
            astutil.get_arg(spec_src, None, "out_specs"), ctx)
        if not in_shapes or out_shapes is None or not out_shapes:
            continue  # unresolvable (or spec-less): never guess
        scratch = _scratch_blocks(spec_src, ctx)
        if scratch is None and spec_src is not call:
            scratch = _scratch_blocks(call, ctx)
        if scratch is None:
            continue
        dtype = _out_dtype(call, ctx)
        in_dts = _in_dtypes(call, ctx, len(in_shapes)) or \
            [dtype] * len(in_shapes)
        fits, est = fits_vmem(list(zip(in_shapes, in_dts)),
                              [(s, dtype) for s in out_shapes],
                              scratch)
        if not fits:
            out.append(Diagnostic(
                rule="A3", slug="vmem", severity=Severity.ERROR,
                path=ctx.path, line=call.lineno, col=call.col_offset,
                message=(f"estimated VMEM for this pallas_call is "
                         f"{est / _MB:.1f} MB > the ~"
                         f"{VMEM_BUDGET_BYTES / _MB:.0f} MB scoped-vmem "
                         "budget (double-buffered blocks + scratch + "
                         "fp32 compute temps); the rms block_rows=256 @ "
                         "H=4096 fp32 pick failed exactly this way on "
                         "chip"),
                hint="shrink the block (halve rows until it fits — see "
                     "fused_norm.pick_block_rows) or annotate with "
                     "`# tpu-lint: vmem-ok` if the estimate is wrong "
                     "for this kernel"))
    return out
