"""Rules B3/B4/B5 — serving-stack consistency invariants.

B3  fault-point   every `utils/faults.py` point name fired/armed by a
                  literal must be registered somewhere in the package,
                  and every `register_point("...")` must appear in
                  SERVING.md's "Fault injection points" table — doc
                  drift is a finding (PR-18 registered
                  `serving.engine.multi_decode_step` without a row).
B4  refusal       typed feature-conflict refusals live in ONE place:
                  `serving/errors.py::FEATURE_CONFLICTS` +
                  `check_feature_conflicts` (ROADMAP item 4). A
                  `raise UnsupportedFeature(...)` — or a
                  ValueError/RuntimeError worded like one ("mutually
                  exclusive", "not supported yet") — anywhere else is
                  a scattered refusal.
B5  metric        counters incremented against a class's literal
                  `self.counters = {...}` registry (or against
                  `*.metrics.counters`, i.e. ServingMetrics) must use
                  registered keys; reservoir reads must name a
                  registered reservoir. The static counterpart of
                  tests/test_metrics_exposition.py's runtime bijection
                  — an unregistered key KeyErrors at increment time,
                  on whatever rare path reaches it.

Cross-file context (the fault registry, SERVING.md, the ServingMetrics
registry) is discovered by walking UP from the linted file and cached
per lint process; files outside a repo checkout (fixtures fed through
lint_source with a fake path) simply skip the cross-file halves.
"""
from __future__ import annotations

import ast
import os
import re

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule

_REG_RE = re.compile(r"register_point\(\s*[\"']([^\"']+)[\"']")
_DOC_ROW_RE = re.compile(r"^\|\s*`([^`]+)`")
_CONFLICT_PHRASES = ("mutually exclusive", "not supported yet")

_FAULT_ROOT_CACHE: dict = {}
_DOC_CACHE: dict = {}
_METRICS_REG_CACHE: dict = {}


def _walk_up(path, candidates, max_up=8):
    """First existing `<ancestor>/<candidate>` above `path`, or None."""
    d = os.path.dirname(os.path.abspath(path))
    for _ in range(max_up):
        for rel in candidates:
            cand = os.path.join(d, *rel.split("/"))
            if os.path.isfile(cand):
                return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


# ------------------------------------------------------------------ B3
def _registered_points(ctx):
    """Every `register_point("...")` literal in the package owning
    `ctx.path` (regex sweep, cached per package root), or None when the
    file is outside a checkout."""
    faults_py = _walk_up(ctx.path, ("paddle_tpu/utils/faults.py",
                                    "utils/faults.py"))
    if faults_py is None:
        return None
    root = os.path.dirname(os.path.dirname(faults_py))
    if root not in _FAULT_ROOT_CACHE:
        names = set()
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                try:
                    with open(os.path.join(dirpath, fn), "r",
                              encoding="utf-8") as f:
                        names.update(_REG_RE.findall(f.read()))
                except OSError:
                    continue
        _FAULT_ROOT_CACHE[root] = names
    return _FAULT_ROOT_CACHE[root]


def _documented_points(ctx):
    """Point names in SERVING.md's "Fault injection points" table, or
    None when no SERVING.md is reachable from `ctx.path`."""
    md = _walk_up(ctx.path, ("SERVING.md",))
    if md is None:
        return None
    if md not in _DOC_CACHE:
        names, in_section = set(), False
        try:
            with open(md, "r", encoding="utf-8") as f:
                for line in f:
                    if line.startswith("## "):
                        in_section = "fault injection points" \
                            in line.lower()
                        continue
                    if in_section:
                        m = _DOC_ROW_RE.match(line)
                        if m:
                            names.add(m.group(1))
        except OSError:
            names = set()
        _DOC_CACHE[md] = names
    return _DOC_CACHE[md]


@register_rule(
    "B3", ("fault-point",), Severity.ERROR,
    "fault points fired but never registered / registered but missing "
    "from SERVING.md's fault table")
def check_fault_points(ctx):
    if ctx.is_test:
        return []
    local_reg = {}      # name -> defining node (this file)
    uses = []           # (name, node) for fire/inject/injected literals
    for n in ast.walk(ctx.tree):
        if not isinstance(n, ast.Call) or not n.args:
            continue
        name = astutil.dotted_name(n.func) or ""
        leaf = name.split(".")[-1]
        arg = n.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue        # module-constant args are registered by
            # construction (`FAULT_X = faults.register_point("...")`)
        if leaf == "register_point" and "faults" in name.split("."):
            local_reg.setdefault(arg.value, arg)
        elif leaf in ("fire", "inject", "injected") \
                and "faults" in name.split("."):
            uses.append((arg.value, arg))
    if not local_reg and not uses:
        return []
    out = []
    registered = _registered_points(ctx)
    if registered is not None:
        known = registered | set(local_reg)
        for pname, node in uses:
            if pname in known:
                continue
            out.append(Diagnostic(
                rule="B3", slug="fault-point", severity=Severity.ERROR,
                path=ctx.path, line=node.lineno, col=node.col_offset,
                message=(f"fault point {pname!r} is fired/armed but "
                         "never registered: fire() silently no-ops and "
                         "inject() raises KeyError, so the fault "
                         "coverage this site promises does not exist"),
                hint="faults.register_point(...) it at import time "
                     "(and document it in SERVING.md's fault table)"))
    documented = _documented_points(ctx)
    if documented is not None:
        for pname, node in sorted(local_reg.items()):
            if pname in documented:
                continue
            out.append(Diagnostic(
                rule="B3", slug="fault-point", severity=Severity.ERROR,
                path=ctx.path, line=node.lineno, col=node.col_offset,
                message=(f"fault point {pname!r} is registered here but "
                         "missing from SERVING.md's \"Fault injection "
                         "points\" table: the soak/resilience contract "
                         "drifts from the docs"),
                hint="add a table row (site, armed semantics, "
                     "trace-visible signal) to SERVING.md"))
    return out


# ------------------------------------------------------------------ B4
def _raise_text(call):
    """Best-effort literal text of a raise's first argument (plain
    string, f-string constants, implicit concatenation)."""
    if not call.args:
        return ""
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        return "".join(v.value for v in arg.values
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str))
    return ""


@register_rule(
    "B4", ("refusal",), Severity.ERROR,
    "feature-conflict refusals raised outside the central "
    "FEATURE_CONFLICTS table")
def check_refusals(ctx):
    if ctx.is_test:
        return []
    # the one legitimate home: the module DEFINING the table (errors.py)
    for n in ctx.tree.body:
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FEATURE_CONFLICTS"
                for t in n.targets):
            return []
    out = []
    for n in ast.walk(ctx.tree):
        if not isinstance(n, ast.Raise) or not isinstance(n.exc, ast.Call):
            continue
        leaf = (astutil.dotted_name(n.exc.func) or "").split(".")[-1]
        if leaf == "UnsupportedFeature":
            why = "raises the typed UnsupportedFeature directly"
        elif leaf in ("ValueError", "RuntimeError"):
            text = _raise_text(n.exc).lower()
            if not any(p in text for p in _CONFLICT_PHRASES):
                continue
            why = f"{leaf} worded as a feature-conflict refusal"
        else:
            continue
        out.append(Diagnostic(
            rule="B4", slug="refusal", severity=Severity.ERROR,
            path=ctx.path, line=n.lineno, col=n.col_offset,
            message=(f"scattered feature refusal ({why}): capability "
                     "conflicts must be declared in serving/errors.py::"
                     "FEATURE_CONFLICTS and raised through "
                     "check_feature_conflicts so ONE table defines what "
                     "this build refuses (ROADMAP item 4)"),
            hint="add the pair to FEATURE_CONFLICTS and call "
                 "check_feature_conflicts(active_features) instead; "
                 "`# tpu-lint: refusal-ok` for non-capability raises "
                 "that merely share the wording"))
    return out


# ------------------------------------------------------------------ B5
def _subscript_keys(node):
    """Literal string key(s) of a subscript: a Constant, or both arms
    of a constant IfExp (procfleet's `"requests_lost" if ... else ...`
    idiom)."""
    s = node.slice
    if isinstance(s, ast.Constant) and isinstance(s.value, str):
        return [(s.value, s)]
    if isinstance(s, ast.IfExp):
        out = []
        for arm in (s.body, s.orelse):
            if isinstance(arm, ast.Constant) and isinstance(arm.value, str):
                out.append((arm.value, arm))
        return out
    return []


def _dict_str_keys(node):
    if not isinstance(node, ast.Dict):
        return None
    keys = set()
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
    return keys


def _class_counter_registry(cls):
    """Literal keys of `self.counters = {...}` (plus
    `self.counters.update({...})`) in the class, or None when the class
    declares no literal registry — only classes that OWN a registry are
    checked, so ad-hoc dict plumbing elsewhere stays out of scope."""
    keys = None
    for n in ast.walk(cls):
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "counters" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    found = _dict_str_keys(n.value)
                    if found is not None:
                        keys = (keys or set()) | found
        elif isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func) or ""
            if name == "self.counters.update" and n.args:
                found = _dict_str_keys(n.args[0])
                if found is not None:
                    keys = (keys or set()) | found
    return keys


def _serving_metrics_registry(ctx):
    """ServingMetrics' counter registry, parsed once from the
    serving/metrics.py reachable above `ctx.path` (None off-checkout)."""
    mpath = _walk_up(ctx.path, ("paddle_tpu/serving/metrics.py",
                                "serving/metrics.py", "metrics.py"))
    if mpath is None:
        return None
    if mpath not in _METRICS_REG_CACHE:
        reg = None
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
            for cls in ast.walk(tree):
                if isinstance(cls, ast.ClassDef) \
                        and cls.name == "ServingMetrics":
                    reg = _class_counter_registry(cls)
        except (OSError, SyntaxError, ValueError):
            reg = None
        _METRICS_REG_CACHE[mpath] = reg
    return _METRICS_REG_CACHE[mpath]


def _metric_diag(ctx, key, node, registry_desc):
    return Diagnostic(
        rule="B5", slug="metric", severity=Severity.ERROR,
        path=ctx.path, line=node.lineno, col=node.col_offset,
        message=(f"counter {key!r} is not registered in "
                 f"{registry_desc}: the increment KeyErrors at runtime "
                 "on whatever rare path reaches it, and the exposition "
                 "layer never reports the metric"),
        hint="add the key (zero-initialized) to the registry dict; "
             "`# tpu-lint: metric-ok` for deliberately dynamic keys")


@register_rule(
    "B5", ("metric",), Severity.ERROR,
    "counters/reservoirs referenced but absent from their exposition "
    "registry")
def check_metrics(ctx):
    if ctx.is_test:
        return []
    out = []
    serving_reg = None
    serving_reg_loaded = False
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        registry = _class_counter_registry(cls)
        reservoirs = set()
        for n in ast.walk(cls):
            if isinstance(n, ast.Call):
                name = astutil.dotted_name(n.func) or ""
                if name.endswith(".add_reservoir") and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    reservoirs.add(n.args[0].value)
        for n in ast.walk(cls):
            if isinstance(n, ast.Subscript):
                target = astutil.dotted_name(n.value) or ""
                if target == "self.counters" and registry is not None:
                    for key, knode in _subscript_keys(n):
                        if key not in registry:
                            out.append(_metric_diag(
                                ctx, key, knode,
                                f"{cls.name}'s self.counters registry"))
                elif target.endswith(".metrics.counters"):
                    if not serving_reg_loaded:
                        serving_reg = _serving_metrics_registry(ctx)
                        serving_reg_loaded = True
                    if serving_reg is not None:
                        for key, knode in _subscript_keys(n):
                            if key not in serving_reg:
                                out.append(_metric_diag(
                                    ctx, key, knode,
                                    "ServingMetrics' counter registry "
                                    "(serving/metrics.py)"))
            elif isinstance(n, ast.Call) and reservoirs:
                name = astutil.dotted_name(n.func) or ""
                if name == "self.reservoir_percentiles" and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str) \
                        and n.args[0].value not in reservoirs:
                    out.append(Diagnostic(
                        rule="B5", slug="metric", severity=Severity.ERROR,
                        path=ctx.path, line=n.args[0].lineno,
                        col=n.args[0].col_offset,
                        message=(f"reservoir {n.args[0].value!r} is read "
                                 f"but {cls.name} never add_reservoir()s "
                                 "it: percentiles come back empty "
                                 "forever"),
                        hint="register it with add_reservoir(...) next "
                             "to the others"))
    # one finding per missing key, not one per reference
    seen, uniq = set(), []
    for d in out:
        if d.message in seen:
            continue
        seen.add(d.message)
        uniq.append(d)
    return uniq
