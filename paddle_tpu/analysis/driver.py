"""tpu-lint drivers: parse a file once, run the selected rules, apply
escape hatches.

Escape-hatch syntax (ANALYSIS.md):
    # tpu-lint: <slug>-ok          suppress that slug on this line
    # tpu-lint: ok                 suppress every rule on this line
    # tpu-lint: skip-file          skip the whole file
A hatch comment counts for the line it sits on AND the next line, so it
can ride above a flagged expression or at the end of it.

Hint syntax (ISSUE 6 — refines a rule instead of suppressing it):
    # tpu-lint-hint: key=value[; key=value]
Hints attach to their line; rules look them up over a node's whole
source span (`FileContext.hint_for`), so a hint can sit anywhere inside
a multi-line pallas_call. Current consumer: A3's `vmem-dtypes` — a
comma list naming each in_spec's TRUE element dtype (int8/int4
quantized kernels would otherwise be budgeted at the out dtype's
width, over- or under-estimating the blocks the estimator exists to
check)."""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

from . import astutil
from .diagnostics import Diagnostic
from .registry import all_rules

__all__ = ["FileContext", "lint_source", "lint_file", "lint_paths",
           "iter_python_files"]

_HATCH_RE = re.compile(r"#\s*tpu-lint:\s*([A-Za-z0-9_,\- ]+)")
_HINT_RE = re.compile(r"#\s*tpu-lint-hint:\s*(.+)")


def _parse_hint_value(raw):
    """`key=value[; key=value]` -> {key: value} (empty when malformed)."""
    kv = {}
    for part in raw.split(";"):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        if k.strip():
            kv[k.strip().lower()] = v.strip()
    return kv


def _parse_directives(source):
    """(hatches, hints): line (1-based) -> hatch-token set / hint dict,
    both from ONE tokenize pass over the file's REAL comment tokens —
    not a substring scan of raw lines: a docstring or test string that
    merely QUOTES either syntax must not suppress or hint anything. On
    a tokenize failure the file simply has no directives (for hatches
    that is the conservative direction: more findings, never fewer;
    losing a hint only falls back to the out-dtype estimate)."""
    hatches, hints = {}, {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _HINT_RE.search(tok.string)
            if m:
                kv = _parse_hint_value(m.group(1))
                if kv:
                    hints.setdefault(tok.start[0], {}).update(kv)
                continue    # "tpu-lint-hint:" must not match _HATCH_RE
            m = _HATCH_RE.search(tok.string)
            if m:
                toks = {t.strip().lower() for t in m.group(1).split(",")
                        if t.strip()}
                if toks:
                    hatches.setdefault(tok.start[0], set()).update(toks)
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return {}, {}
    return hatches, hints


@dataclass
class FileContext:
    path: str
    source: str
    tree: ast.AST
    lines: list
    is_test: bool
    consts: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)
    hatches: dict = field(default_factory=dict)
    hints: dict = field(default_factory=dict)

    @property
    def skip_file(self):
        return any("skip-file" in toks for toks in self.hatches.values())

    def hint_for(self, node, key):
        """The `# tpu-lint-hint: key=...` value attached to any line of
        `node`'s source span (plus one line above, mirroring the hatch
        window), or None."""
        end = getattr(node, "end_lineno", None) or node.lineno
        for line in range(node.lineno - 1, end + 1):
            kv = self.hints.get(line)
            if kv and key in kv:
                return kv[key]
        return None

    def suppressed(self, diag: Diagnostic):
        for line in (diag.line, diag.line - 1):
            toks = self.hatches.get(line)
            if toks and ("ok" in toks or f"{diag.slug}-ok" in toks):
                return True
        return False


def _infer_is_test(path):
    parts = os.path.normpath(path).split(os.sep)
    base = os.path.basename(path)
    return ("tests" in parts or base.startswith("test_")
            or base == "conftest.py")


def lint_source(source, path="<string>", rules=None, is_test=None):
    """Lint one source string. Returns a sorted diagnostic list.
    Syntax errors produce a single parse-error diagnostic rather than
    raising (the linter must be runnable over arbitrary trees)."""
    if rules is None:
        rules = all_rules()
    if is_test is None:
        is_test = _infer_is_test(path)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Diagnostic(rule="parse", slug="parse", severity="error",
                           path=path, line=int(e.lineno or 0),
                           message=f"syntax error: {e.msg}")]
    lines = source.splitlines()
    hatches, hints = _parse_directives(source)
    ctx = FileContext(
        path=path, source=source, tree=tree, lines=lines, is_test=is_test,
        consts=astutil.module_int_consts(tree),
        functions=astutil.local_functions(tree),
        hatches=hatches, hints=hints)
    if ctx.skip_file:
        return []
    out = []
    for rule in rules:
        for diag in rule.check(ctx):
            if not ctx.suppressed(diag):
                out.append(diag)
    out.sort(key=Diagnostic.sort_key)
    return out


def lint_file(path, rules=None, is_test=None):
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path=path, rules=rules, is_test=is_test)


def iter_python_files(paths, exclude=()):
    """Yield .py files under `paths` (files or directories), sorted,
    skipping any whose path contains an `exclude` substring."""
    seen = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                seen.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        seen.append(os.path.join(root, fn))
    for p in seen:
        norm = p.replace(os.sep, "/")
        if any(x in norm for x in exclude):
            continue
        yield p


def lint_paths(paths, rules=None, exclude=(), is_test=None):
    """Lint every .py file under `paths`. Returns (diagnostics,
    files_scanned)."""
    diags = []
    n = 0
    for path in iter_python_files(paths, exclude=exclude):
        n += 1
        diags.extend(lint_file(path, rules=rules, is_test=is_test))
    diags.sort(key=Diagnostic.sort_key)
    return diags, n
