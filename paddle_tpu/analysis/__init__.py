"""paddle_tpu.analysis — tpu-lint: static trace-safety analysis.

An AST-based analyzer that turns the round-4 chip-landmine catalog into
enforced invariants runnable in CI on CPU (no jax import, no TPU
grant). Rule pack:

  A1  index-map   bare int literals / python `//` `%` in BlockSpec
                  index maps (i64-under-x64 + Mosaic convert recursion)
  A2  blockspec   (8, 128)-divisibility of statically-known block dims
  A3  vmem        per-pallas_call scoped-VMEM budget estimate
  A4  interpret / timing-cap
                  interpret=True shipping in non-test code; device-side
                  loops past the 512-iteration wedge cap
  A5  purity      side effects in traced cond branches and scan/while
                  bodies (static half) + runtime promotions recorded by
                  dy2static and the collective layer (purity.py)

B-series (ISSUE 19) — serving/fleet protocol & consistency:

  B1  cache-key   self.<config> read inside a ProgramCache builder but
                  absent from the cache-key derivation
  B2  protocol    mailbox message types sent without a receiver
                  dispatch arm (and dead arms), across the
                  worker/procfleet pair via `protocol-peer=` hints
  B3  fault-point fired-but-unregistered fault points; registered
                  points missing from SERVING.md's fault table
  B4  refusal     feature-conflict raises outside serving/errors.py's
                  FEATURE_CONFLICTS table (ROADMAP item 4)
  B5  metric      counters/reservoirs referenced but absent from their
                  exposition registries

CLI: tools/tpu_lint.py (`make lint`). Docs: ANALYSIS.md. Fixture
corpus: tests/lint_fixtures/ via tests/test_tpu_lint.py.

This package is stdlib-only BY CONTRACT — importing jax (or anything
that imports jax) here would claim the TPU grant from the lint CLI and
blow the <60 s CI budget.
"""
from .diagnostics import Diagnostic, Severity, format_text  # noqa: F401
from .registry import Rule, all_rules, select_rules  # noqa: F401
from . import purity  # noqa: F401
from . import vmem  # noqa: F401
# importing the rule modules registers them
from . import rules_index_map  # noqa: F401
from . import rules_blockspec  # noqa: F401
from . import rules_runtime  # noqa: F401
from . import rules_purity  # noqa: F401
from . import rules_cachekey  # noqa: F401
from . import rules_protocol  # noqa: F401
from . import rules_serving  # noqa: F401
from .driver import (  # noqa: F401
    FileContext, iter_python_files, lint_file, lint_paths, lint_source)

__all__ = [
    "Diagnostic", "Severity", "format_text", "Rule", "all_rules",
    "select_rules", "purity", "vmem", "FileContext", "iter_python_files",
    "lint_file", "lint_paths", "lint_source",
]
