"""Per-`pallas_call` VMEM budget estimator (rule A3).

Model (cross-checked against the round-4 chip data points, see
tests/test_tpu_lint.py::TestVmemCrossCheck):

    vmem_bytes = sum(in  blocks: elems * width * depth)   # double-buffered
               + sum(out blocks: elems * width * depth)   #   DMA pipeline
               + sum(scratch    : elems * width)          # single-buffered
               + fp32_copies * max_block_elems * 4        # compute temps
               + extra_bytes                              # kernel-specific

`depth=2` is Mosaic's default double buffering of streamed blocks;
`fp32_copies=2` models the upcast-input + result fp32 temporaries a
kernel computing in fp32 materializes per block (the rms kernel's
chip-measured "scoped vmem 24.2M > 16M" at block (256, 4096) fp32 is
reproduced by exactly this accounting: 8 MB x-in + 8 MB out + 2x4 MB
temps); `extra_bytes` carries kernel-shaped intermediates the block
specs cannot see (e.g. a flash-attention (block_q, block_k) fp32 score
tile).

The estimate is deliberately a LOWER bound heuristic: it exists to
catch order-of-magnitude OOMs on CPU before they burn chip time, not to
replace Mosaic's allocator. Anything statically unresolvable is skipped
by the AST rule rather than guessed.
"""
from __future__ import annotations

import math

__all__ = ["VMEM_BUDGET_BYTES", "DTYPE_BYTES", "estimate_vmem_bytes",
           "fits_vmem"]

# v5e VMEM is 128 MB/core but Mosaic's per-kernel scoped-vmem budget is
# ~16 MB (the chip error was "scoped vmem 24.2M > 16M").
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
    # sub-byte packed dtypes (quantized kernels, ISSUE 6): fractional
    # widths are fine — _block_bytes rounds the BLOCK total up, which
    # is what a packed layout actually costs
    "int4": 0.5, "uint4": 0.5,
}


def _block_bytes(block):
    shape, dtype = block
    width = DTYPE_BYTES.get(str(dtype))
    if width is None:
        raise ValueError(f"unknown dtype {dtype!r}")
    elems = math.prod(int(d) for d in shape)
    return int(math.ceil(elems * width)), elems


def estimate_vmem_bytes(in_blocks, out_blocks, scratch=(), depth=2,
                        fp32_copies=2, extra_bytes=0):
    """Estimated VMEM bytes for one pallas_call.

    in_blocks/out_blocks/scratch: iterables of (shape, dtype_str) —
    BLOCK shapes (per grid step), not array shapes.
    """
    total = 0
    max_elems = 0
    for block in in_blocks:
        b, e = _block_bytes(block)
        total += b * depth
        max_elems = max(max_elems, e)
    for block in out_blocks:
        b, e = _block_bytes(block)
        total += b * depth
        max_elems = max(max_elems, e)
    for block in scratch:
        b, _ = _block_bytes(block)
        total += b
    total += fp32_copies * max_elems * 4
    total += int(extra_bytes)
    return total


def fits_vmem(in_blocks, out_blocks, scratch=(), depth=2, fp32_copies=2,
              extra_bytes=0, budget=VMEM_BUDGET_BYTES):
    """(fits, estimated_bytes) against the scoped-vmem budget."""
    est = estimate_vmem_bytes(in_blocks, out_blocks, scratch, depth,
                              fp32_copies, extra_bytes)
    return est <= budget, est
