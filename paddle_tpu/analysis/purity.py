"""Trace-purity vocabulary + runtime diagnostic recorder (rule A5).

This is the PROMOTION of dy2static's mutation/side-effect detection
into reportable diagnostics: the canonical name sets live here (and
`jit/dy2static.py` imports them back, so the linter and the converter
can never drift), and the runtime events that used to be only warnings
or silent declines — a `print` in a scan/while-lowered body, a loop
kept eager because its body mutates non-carried python state, an
out-of-trace collective on a >1-rank group — now also record a shared
`Diagnostic` that `jit.to_static_report()` exposes and
`tools/fallback_report.py --lint` renders into FALLBACKS.md.

Stdlib-only (see diagnostics.py docstring for why).
"""
from __future__ import annotations

import ast
import threading

from .diagnostics import Diagnostic, Severity

__all__ = [
    "SIDE_EFFECT_BUILTINS", "MUTATOR_METHODS", "side_effect_calls",
    "record", "drain", "snapshot", "reset", "set_context", "clear_context",
    "record_loop_side_effect", "record_loop_mutation",
    "record_out_of_trace_collective", "record_spmd_rule_failure",
]

# Pure-output builtins that are invisible to the mutation checks but run
# ONCE at trace time inside a compiled loop body (dy2static module
# docstring, ADVICE r5 #1).
SIDE_EFFECT_BUILTINS = frozenset({"print", "breakpoint", "input"})

# Container mutator methods: a call `x.append(...)` on non-carried state
# inside a trace-once body runs once, not per iteration (dy2static
# `_has_uncarried_mutation`).
MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "remove", "clear", "sort", "reverse",
    "discard", "update", "setdefault", "popitem", "appendleft",
    "popleft", "pop",
})


def side_effect_calls(node):
    """AST sweep shared by the static A5 rule: (name, lineno) for every
    side-effecting call in `node` — SIDE_EFFECT_BUILTINS by name,
    container mutator methods, setattr/delattr, and paddle in-place ops
    (trailing single underscore). Nested defs/lambdas ARE descended:
    a cond branch runs everything it closes over."""
    found = []
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name):
            if f.id in SIDE_EFFECT_BUILTINS or f.id in ("setattr", "delattr"):
                found.append((f.id, n.lineno))
        elif isinstance(f, ast.Attribute):
            if f.attr in MUTATOR_METHODS or (
                    f.attr.endswith("_") and not f.attr.endswith("__")):
                found.append((f.attr, n.lineno))
    return found


# --------------------------------------------------------------- recorder
_LOCK = threading.Lock()
_DIAGS: list = []
_SEEN: set = set()  # (slug, path, line, message) dedup: a retraced
#                     function (guard miss per shape/dtype/grad mode)
#                     re-runs the converter and would re-record the
#                     same event every time
_MAX = 256          # bounded like jit.api's _fallback_registry
_DROPPED = [0]
# (path, first_lineno, qualname) of the function dy2static is currently
# converting — stamped by _convert so AST-relative linenos can be mapped
# back to real file positions.
_CTX = threading.local()


def set_context(path, first_line, qualname):
    _CTX.value = (path or "<unknown>", int(first_line or 1), qualname)


def clear_context():
    _CTX.value = None


def _context():
    return getattr(_CTX, "value", None)


def record(diag: Diagnostic):
    key = (diag.slug, diag.path, diag.line, diag.message)
    with _LOCK:
        if key in _SEEN:
            return
        _SEEN.add(key)
        if len(_DIAGS) >= _MAX:
            del _DIAGS[0]
            _DROPPED[0] += 1
        _DIAGS.append(diag)


def snapshot():
    """Copy of the recorded diagnostics (does not clear)."""
    with _LOCK:
        return list(_DIAGS)


def drain():
    """Return and clear the recorded diagnostics (dedup window too: a
    recurrence after a drain is a new report)."""
    with _LOCK:
        out = list(_DIAGS)
        _DIAGS.clear()
        _SEEN.clear()
        return out


def reset():
    with _LOCK:
        _DIAGS.clear()
        _SEEN.clear()
        _DROPPED[0] = 0


def dropped():
    return _DROPPED[0]


# ----------------------------------------------------- event constructors
def record_loop_side_effect(builtins_found, kind, path, line, funcname):
    record(Diagnostic(
        rule="A5", slug="loop-side-effect", severity=Severity.WARNING,
        path=path or "<unknown>", line=int(line or 0), source="runtime",
        message=(f"loop body of {funcname}() calling "
                 f"{', '.join(sorted(builtins_found))}() was compiled to a "
                 f"{kind}: the call ran once at trace time, not per "
                 "iteration"),
        hint="wrap the loop in paddle.jit.not_to_static or drop the call"))


def record_loop_mutation(rel_line, kind):
    """A dy2static loop rewrite declined because the body (or while
    test) mutates non-carried python state — the loop stays eager by
    design; surface WHERE so the cost is visible."""
    ctx = _context()
    if ctx is None:
        path, base, fname = "<unknown>", 1, "<unknown>"
    else:
        path, base, fname = ctx
    record(Diagnostic(
        rule="A5", slug="loop-mutation", severity=Severity.WARNING,
        path=path, line=base + max(int(rel_line) - 1, 0), source="runtime",
        message=(f"{kind} in {fname}() kept as an eager python loop: its "
                 "body mutates python state that is not loop-carried "
                 "(a trace-once conversion would run the mutation once, "
                 "not per iteration)"),
        hint="carry the state through the loop (reassign the name) or "
             "accept the eager fallback"))


def record_spmd_rule_failure(op_name, error, traceback_text=None):
    """An SPMD propagation rule raised (FLAGS_spmd_debug routing, ISSUE
    12): the failure used to be a bare print() — machine-readable here
    so `to_static_report()["purity_diagnostics"]` carries it. Advisory
    by contract: the rule never breaks compute (GSPMD owns
    correctness), this records WHICH rule is broken."""
    msg = f"SPMD rule '{op_name}' failed: {error}"
    if traceback_text:
        msg += "\n" + str(traceback_text).rstrip()
    record(Diagnostic(
        rule="A5", slug="spmd-rule", severity=Severity.WARNING,
        path="<runtime>", line=0, source="runtime", message=msg,
        hint="the op fell back to GSPMD whole-program propagation; fix "
             "or unregister the rule (rule_stats()['last_error'] keeps "
             "the latest repr per op)"))


def record_out_of_trace_collective(name, nranks, axis):
    record(Diagnostic(
        rule="A5", slug="collective", severity=Severity.ERROR,
        path="<runtime>", line=0, source="runtime",
        message=(f"{name} on a {nranks}-rank group (axis={axis!r}) was "
                 "called outside a mesh-bound trace — it would silently "
                 "return local data, so it raised"),
        hint="run the collective inside shard_map/to_static with the "
             "axis bound, or use GSPMD sharding constraints"))
