"""Shared diagnostic type for tpu-lint (static rules AND runtime
promotions from dy2static / the collective layer).

Deliberately stdlib-only: the linter must run on a cold CPU interpreter
in CI without importing jax (no TPU grant, <60 s budget — see
ANALYSIS.md), and the runtime recorders in `paddle_tpu.jit.dy2static` /
`paddle_tpu.distributed.collective` import this module from inside the
package, so it must stay dependency-free in both directions.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict

__all__ = ["Severity", "Diagnostic", "format_text"]


class Severity:
    """String severities (not an Enum: JSON output stays plain)."""
    ERROR = "error"
    WARNING = "warning"
    _ORDER = {ERROR: 0, WARNING: 1}

    @classmethod
    def rank(cls, sev):
        return cls._ORDER.get(sev, 99)


@dataclass
class Diagnostic:
    """One finding: rule id (A1..A5), slug (the escape-hatch token —
    `# tpu-lint: <slug>-ok` suppresses it), severity, location, message
    and a fix hint. Runtime-recorded diagnostics (dy2static purity
    promotions) use the same type so FALLBACKS.md and the CLI render
    identically."""
    rule: str
    slug: str
    severity: str
    path: str
    line: int
    message: str
    col: int = 0
    hint: str = ""
    source: str = "static"  # "static" (AST rule) | "runtime" (recorder)

    def to_dict(self):
        return asdict(self)

    def format(self):
        loc = f"{self.path}:{self.line}:{self.col}"
        head = f"{loc}: {self.severity} {self.rule}[{self.slug}] {self.message}"
        if self.hint:
            head += f"\n    hint: {self.hint}"
        return head

    def sort_key(self):
        return (self.path, self.line, self.col,
                Severity.rank(self.severity), self.rule)


def format_text(diags):
    """Render a diagnostic list the way the CLI prints it."""
    return "\n".join(d.format() for d in
                     sorted(diags, key=Diagnostic.sort_key))
