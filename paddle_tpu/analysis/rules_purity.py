"""Rule A5 (static half) — trace-purity diagnostics.

Promotes dy2static's side-effect vocabulary (purity.py, imported back
by `jit/dy2static.py`) into lintable rules:

  * side effects in `static.nn.cond` branches: a traced cond executes
    BOTH branches and selects, so branch side effects run twice by
    design (round-3 notes) — mutations or prints in a branch are a
    correctness smell;
  * `print`/`breakpoint`/`input` in a body passed to lax.scan /
    while_loop / fori_loop: the body is traced ONCE, so the call fires
    once with tracer values, not per iteration (ADVICE r5 #1 — the
    runtime warning in dy2static records the same diagnostic when it
    actually happens; this rule catches it before it runs).

The runtime half (loop-mutation declines, out-of-trace collectives on
>1-rank groups) cannot be seen statically with zero false positives;
those record diagnostics through purity.record_* at the moment they
happen and surface via `jit.to_static_report()` /
`tools/fallback_report.py --lint`.
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .purity import SIDE_EFFECT_BUILTINS, side_effect_calls
from .registry import register_rule

_SLUG = "purity"


def _branch_fns(node, ctx):
    """Callables for a cond/loop argument node: lambdas inside it plus
    a same-file function passed by name."""
    if node is None:
        return []
    fns = list(astutil.lambdas_in(node))
    if isinstance(node, ast.Name) and node.id in ctx.functions:
        fns.append(ctx.functions[node.id])
    return fns


def _is_static_cond(name):
    parts = name.split(".")
    return parts[-1] == "cond" and len(parts) > 1 \
        and any(p in ("nn", "static") for p in parts[:-1])


_LOOP_BODY_ARGS = {
    # leaf name -> [(positional idx, kwarg name), ...] of traced bodies
    "scan": [(0, "f")],
    "while_loop": [(0, "cond_fun"), (1, "body_fun"), (0, "cond_fn"),
                   (1, "body_fn")],
    "fori_loop": [(2, "body_fun")],
}


@register_rule(
    "A5", (_SLUG,), Severity.WARNING,
    "side effects in traced cond branches / scan-while-lowered bodies")
def check_trace_purity(ctx):
    out = []
    seen = set()
    for n in ast.walk(ctx.tree):
        if not isinstance(n, ast.Call):
            continue
        name = astutil.dotted_name(n.func) or ""
        leaf = name.split(".")[-1]
        if _is_static_cond(name):
            for arg_node in (astutil.get_arg(n, 1, "true_fn"),
                             astutil.get_arg(n, 2, "false_fn")):
                for fn in _branch_fns(arg_node, ctx):
                    for eff, line in side_effect_calls(fn):
                        key = (line, eff, "cond")
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(Diagnostic(
                            rule="A5", slug=_SLUG,
                            severity=Severity.WARNING,
                            path=ctx.path, line=line,
                            message=(f"`{eff}` inside a static.nn.cond "
                                     "branch: a traced cond executes "
                                     "BOTH branches and selects, so this "
                                     "side effect runs twice by design"),
                            hint="make branches pure; do side effects "
                                 "after the select"))
        elif leaf in _LOOP_BODY_ARGS:
            for idx, kwname in _LOOP_BODY_ARGS[leaf]:
                for fn in _branch_fns(astutil.get_arg(n, idx, kwname), ctx):
                    for eff, line in side_effect_calls(fn):
                        if eff not in SIDE_EFFECT_BUILTINS:
                            continue  # mutations in jax loop bodies are
                            # the body fn's own business (carried state)
                        key = (line, eff, leaf)
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(Diagnostic(
                            rule="A5", slug=_SLUG,
                            severity=Severity.WARNING,
                            path=ctx.path, line=line,
                            message=(f"`{eff}` inside a {leaf} body: the "
                                     "body is traced once, so this fires "
                                     "once with tracer values, not per "
                                     "iteration"),
                            hint="use jax.debug.print for per-iteration "
                                 "output, or hoist the call out of the "
                                 "loop"))
    return out
