"""Rule A1 — trace-unsafe BlockSpec index maps.

Chip lessons this encodes (CLAUDE.md round-4 notes):
  * the package enables x64, so a bare int literal returned from a
    BlockSpec index map traces as i64 and Mosaic's func.return fails to
    legalize (found for real in fused_norm.py — hence its `_I0 =
    np.int32(0)` pin);
  * Python `//` (or `%`) on a traced index lowers through an i64
    convert that hits an infinite recursion in Mosaic's convert
    fallback (found on real v5e — flash_attention's `bdiv` uses
    `jax.lax.div` on pinned int32 instead).
interpret=True on CPU hides both failures entirely, which is exactly
why this is a static rule.
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule

_SLUG = "index-map"


def _blockspec_calls(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func) or ""
            if name.split(".")[-1] == "BlockSpec":
                yield n


def _index_fns(call, ctx):
    """Callables acting as the index map of one BlockSpec: every Lambda
    inside the index_map argument (covers wrapper patterns like
    `qmap(lambda ...)`) plus a named function passed by name."""
    arg = astutil.get_arg(call, 1, "index_map")
    if arg is None:
        return []
    fns = list(astutil.lambdas_in(arg))
    if isinstance(arg, ast.Name) and arg.id in ctx.functions:
        fns.append(ctx.functions[arg.id])
    return fns


def _returned_exprs(fn):
    if isinstance(fn, ast.Lambda):
        return [fn.body]
    return [r.value for r in ast.walk(fn)
            if isinstance(r, ast.Return) and r.value is not None]


def _body_nodes(fn):
    """Nodes of the function BODY only — lambda defaults are evaluated
    at definition time (outside the trace) and must not be flagged."""
    if isinstance(fn, ast.Lambda):
        return ast.walk(fn.body)
    nodes = []
    for st in fn.body:
        nodes.extend(ast.walk(st))
    return nodes


def _bare_int(node):
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool))


@register_rule(
    "A1", (_SLUG,), Severity.ERROR,
    "bare int literal or python // / % inside a BlockSpec index map")
def check_index_maps(ctx):
    out = []
    seen = set()  # a lambda can sit under several wrappers; flag once
    for call in _blockspec_calls(ctx.tree):
        for fn in _index_fns(call, ctx):
            key = (fn.lineno, fn.col_offset)
            if key in seen:
                continue
            seen.add(key)
            for ret in _returned_exprs(fn):
                elems = ret.elts if isinstance(ret, (ast.Tuple, ast.List)) \
                    else [ret]
                for e in elems:
                    if _bare_int(e):
                        out.append(Diagnostic(
                            rule="A1", slug=_SLUG, severity=Severity.ERROR,
                            path=ctx.path, line=e.lineno, col=e.col_offset,
                            message=(f"bare int literal {e.value} returned "
                                     "from a BlockSpec index map traces as "
                                     "i64 under package x64 mode; Mosaic "
                                     "rejects i64 index-map results on "
                                     "chip (interpret=True hides this)"),
                            hint="pin it: _I0 = np.int32(0) at module "
                                 "scope and return _I0"))
            for n in _body_nodes(fn):
                if isinstance(n, ast.BinOp) and isinstance(
                        n.op, (ast.FloorDiv, ast.Mod)):
                    opname = "//" if isinstance(n.op, ast.FloorDiv) else "%"
                    out.append(Diagnostic(
                        rule="A1", slug=_SLUG, severity=Severity.ERROR,
                        path=ctx.path, line=n.lineno, col=n.col_offset,
                        message=(f"python `{opname}` inside a BlockSpec "
                                 "index map lowers through an i64 convert "
                                 "that infinitely recurses in Mosaic's "
                                 "convert fallback on chip"),
                        hint="use jax.lax.div / jax.lax.rem on "
                             "np.int32-pinned operands"))
    return out
