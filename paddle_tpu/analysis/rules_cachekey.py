"""Rule B1 — ProgramCache key completeness.

Serving history (PRs 5/6/13/15): every config axis the engine bakes
into a compiled program as a Python constant had to be hand-added to
the program-cache key after the aliasing bit — quant config
(`kv_dtype`/`wq`), the `("tp", tp)` mesh shape, the spec-decode `K`,
the LoRA layout signature. Each omission is silent: two engines (or
one engine and the persistent CompileCache of a previous process)
share a program whose closed-over constants differ.

The rule runs per class: every `self._get_program(key, builder)` /
`self.programs.get(key, builder)` call is paired with its builder
FunctionDef (direct `self._build_x` reference or
`lambda: self._build_x(...)`), and every `self.<attr>` READ inside the
builder must ride the key. "Rides the key" is transitive through
plain `self.X = <expr>` assignments anywhere in the class — the
engine's `self._qkey` aggregate keys `kv_dtype`/`wq`/`tp`/`lora`
without naming them at the call site. Methods/properties defined in
the class body are exempt (they are code, not config), and
`# tpu-lint: cache-key-ok` acknowledges an attr that genuinely cannot
alias (e.g. `self.model` under a per-engine cache whose disk tier
fingerprints the model geometry separately).
"""
from __future__ import annotations

import ast

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule


def _self_attrs(node):
    """Names X for every `self.X` attribute access anywhere in node."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) \
                and isinstance(n.value, ast.Name) and n.value.id == "self":
            out.add(n.attr)
    return out


def _attr_dependencies(cls):
    """attr -> set of self-attrs its assignment(s) read, over every
    `self.X = <expr>` / `self.X += <expr>` in the class body. Feeding
    `self._qkey = (self.kv_dtype, ..., ("tp", self.tp))` through this
    map is what lets a call-site key of `(...) + self._qkey` count
    kv_dtype/wq/tp as keyed."""
    deps = {}
    for n in ast.walk(cls):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            value = n.value
            if value is None:
                continue
            read = _self_attrs(value)
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    deps.setdefault(t.attr, set()).update(read)
    return deps


def _expand_keyed(keyed, deps):
    """Transitive closure of `keyed` through the assignment-dependency
    map (fixpoint; the map is tiny)."""
    out = set(keyed)
    changed = True
    while changed:
        changed = False
        for a in list(out):
            extra = deps.get(a, ())
            if not out.issuperset(extra):
                out.update(extra)
                changed = True
    return out


def _resolve_builder(expr, class_defs):
    """The builder FunctionDef a cache-get call will invoke, or None.
    Handles the two idioms in the tree: `lambda: self._build_x(S, P)`
    and a bare `self._build_x` reference."""
    if isinstance(expr, ast.Lambda) and isinstance(expr.body, ast.Call):
        expr = expr.body.func
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return class_defs.get(expr.attr)
    return None


def _cache_get_calls(cls):
    """(call, key_expr, builder_expr) for every program-cache get in
    the class: `self._get_program(key, builder)` or
    `self.programs.get(key, builder)` (the draft model's per-proposer
    cache uses the latter through its own _get_program)."""
    for n in ast.walk(cls):
        if not isinstance(n, ast.Call) or len(n.args) < 2:
            continue
        name = astutil.dotted_name(n.func) or ""
        if name.endswith("._get_program") or name.endswith(".programs.get"):
            yield n, n.args[0], n.args[1]


@register_rule(
    "B1", ("cache-key",), Severity.ERROR,
    "self.<config> read inside a program builder but absent from its "
    "ProgramCache key")
def check_cache_key(ctx):
    if ctx.is_test:
        return []
    out = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        class_defs = {n.name: n for n in cls.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        deps = None
        flagged = set()
        for call, key_expr, builder_expr in _cache_get_calls(cls):
            builder = _resolve_builder(builder_expr, class_defs)
            if builder is None:
                continue    # forwarding shims (_get_program itself)
            if deps is None:
                deps = _attr_dependencies(cls)
            keyed = _expand_keyed(_self_attrs(key_expr), deps)
            for node in ast.walk(builder):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and isinstance(node.ctx, ast.Load)):
                    continue
                attr = node.attr
                if attr in keyed or attr in class_defs \
                        or (builder.name, attr) in flagged:
                    continue
                flagged.add((builder.name, attr))
                out.append(Diagnostic(
                    rule="B1", slug="cache-key", severity=Severity.ERROR,
                    path=ctx.path, line=node.lineno, col=node.col_offset,
                    message=(f"self.{attr} is read inside program builder "
                             f"{builder.name}() but does not ride its "
                             "cache key: two engines (or a restarted "
                             "process via the persistent CompileCache) "
                             "with different values would share one "
                             "compiled program"),
                    hint=f"add self.{attr} (or an aggregate like "
                         "self._qkey that includes it) to the key tuple, "
                         "or annotate `# tpu-lint: cache-key-ok` with why "
                         "it cannot alias"))
        # `flagged`/`deps` are per-class by construction
    return out
