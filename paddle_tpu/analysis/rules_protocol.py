"""Rule B2 — fleet mailbox protocol exhaustiveness.

The worker/supervisor protocol (serving/fleet/worker.py <->
serving/fleet/procfleet.py over the transport.py Channel) is a hand-
grown set of `chan.send("type", ...)` frames dispatched by
string-compare chains (`mtype = msg.get("type")` ... `elif mtype ==`).
PR-16's torn-send bug class showed how a frame kind added on one side
without its receiver arm fails: the seq-hole repair waits
`hole_timeout_s`, heartbeats heal the visible state, and the missing
handler is a latency mystery instead of an error. This rule makes the
asymmetry a lint finding.

Activation is explicit: a file opts in with
    # tpu-lint-hint: protocol-peer=<filename>
naming its counterpart (resolved relative to the file; `self` for a
single-file protocol). Both directions are checked with UNION
semantics — `Channel.relay` re-sends frames verbatim, so a type
handled by either side counts as handled, a type sent by either side
counts as live:

* a type SENT anywhere but handled nowhere -> ERROR (dead letter)
* a type HANDLED here but sent nowhere    -> WARNING (dead arm)
"""
from __future__ import annotations

import ast
import os

from . import astutil
from .diagnostics import Diagnostic, Severity
from .registry import register_rule

_PEER_CACHE: dict = {}


def _type_vars(tree):
    """Names assigned from `<x>.get("type")` / `<x>["type"]` — the
    dispatch variables the if/elif chains compare against."""
    out = set()
    for n in ast.walk(tree):
        if not isinstance(n, ast.Assign) or len(n.targets) != 1 \
                or not isinstance(n.targets[0], ast.Name):
            continue
        v = n.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "get" and v.args \
                and isinstance(v.args[0], ast.Constant) \
                and v.args[0].value == "type":
            out.add(n.targets[0].id)
        elif isinstance(v, ast.Subscript) \
                and isinstance(v.slice, ast.Constant) \
                and v.slice.value == "type":
            out.add(n.targets[0].id)
    return out


def _str_consts(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            yield from _str_consts(elt)


def _protocol_sets(tree):
    """(sent, handled): message-type -> first ast node using it."""
    sent, handled = {}, {}
    tvars = _type_vars(tree)
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "send" and n.args \
                and isinstance(n.args[0], ast.Constant) \
                and isinstance(n.args[0].value, str):
            sent.setdefault(n.args[0].value, n.args[0])
        elif isinstance(n, ast.Compare) and len(n.ops) == 1:
            sides = []
            if isinstance(n.left, ast.Name) and n.left.id in tvars:
                sides = n.comparators
            elif len(n.comparators) == 1 \
                    and isinstance(n.comparators[0], ast.Name) \
                    and n.comparators[0].id in tvars:
                sides = [n.left]
            if not sides:
                continue
            if isinstance(n.ops[0], (ast.Eq, ast.In)):
                for side in sides:
                    for val, node in _str_consts(side):
                        handled.setdefault(val, node)
    return sent, handled


def _peer_sets(path):
    """Parse the peer file once per lint process; missing/unreadable
    peers contribute empty sets (the hint then degrades to single-file
    checking, which only ADDS findings — the conservative direction)."""
    key = os.path.abspath(path)
    if key not in _PEER_CACHE:
        try:
            with open(key, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
            _PEER_CACHE[key] = _protocol_sets(tree)
        except (OSError, SyntaxError, ValueError):
            _PEER_CACHE[key] = ({}, {})
    return _PEER_CACHE[key]


def _peer_hint(ctx):
    for kv in ctx.hints.values():
        if "protocol-peer" in kv:
            return kv["protocol-peer"]
    return None


@register_rule(
    "B2", ("protocol",), Severity.ERROR,
    "mailbox message types sent without a receiver dispatch arm "
    "(or handled but never sent)")
def check_protocol(ctx):
    peer = _peer_hint(ctx)
    if peer is None:
        return []
    sent, handled = _protocol_sets(ctx.tree)
    if peer == "self" or not os.path.isfile(ctx.path):
        peer_sent, peer_handled = {}, {}
        peer_label = "this file"
    else:
        peer_path = os.path.join(os.path.dirname(ctx.path), peer)
        peer_sent, peer_handled = _peer_sets(peer_path)
        peer_label = peer
    out = []
    for mtype, node in sorted(sent.items()):
        if mtype in handled or mtype in peer_handled:
            continue
        out.append(Diagnostic(
            rule="B2", slug="protocol", severity=Severity.ERROR,
            path=ctx.path, line=node.lineno, col=node.col_offset,
            message=(f"message type {mtype!r} is sent here but no "
                     f"dispatch arm handles it (here or in {peer_label}): "
                     "the frame rides the seq-numbered stream, burns a "
                     "hole-repair timeout on loss, and is then silently "
                     "dropped by the receiver"),
            hint=f"add an `elif mtype == {mtype!r}:` arm to the "
                 "receiver's dispatch, or delete the send; "
                 "`# tpu-lint: protocol-ok` for intentionally "
                 "fire-and-forget frames"))
    for mtype, node in sorted(handled.items()):
        if mtype in sent or mtype in peer_sent:
            continue
        out.append(Diagnostic(
            rule="B2", slug="protocol", severity=Severity.WARNING,
            path=ctx.path, line=node.lineno, col=node.col_offset,
            message=(f"dispatch arm for message type {mtype!r} but "
                     f"nothing (here or in {peer_label}) ever sends it: "
                     "dead protocol arm"),
            hint="wire up the sender or delete the arm; "
                 "`# tpu-lint: protocol-ok` if an external client "
                 "sends it"))
    return out
