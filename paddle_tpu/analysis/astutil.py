"""Small AST helpers shared by the tpu-lint rules (stdlib-only)."""
from __future__ import annotations

import ast

__all__ = ["dotted_name", "get_arg", "lambdas_in", "resolve_int",
           "resolve_shape", "module_int_consts", "dtype_name",
           "local_functions"]


def dotted_name(node):
    """'pl.BlockSpec' for Attribute chains, 'BlockSpec' for Names,
    None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def get_arg(call: ast.Call, idx, kwname):
    """Positional arg idx or keyword kwname of a Call, else None."""
    if idx is not None and len(call.args) > idx:
        a = call.args[idx]
        if not isinstance(a, ast.Starred):
            return a
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    return None


def lambdas_in(node):
    """Every Lambda inside `node` (including `node` itself)."""
    return [n for n in ast.walk(node) if isinstance(n, ast.Lambda)]


_INT_WRAPPERS = {"int32", "int64", "int16", "int8", "int", "uint32"}


def resolve_int(node, consts):
    """Best-effort static int: literals, module-level constants,
    np.int32(...)-style wrappers, unary minus and + - * // % arithmetic
    over resolvable operands. None when unresolvable."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool):
            return None
        return node.value if isinstance(node.value, int) else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = resolve_int(node.operand, consts)
        return -v if v is not None else None
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func) or ""
        if fname.split(".")[-1] in _INT_WRAPPERS and len(node.args) == 1 \
                and not node.keywords:
            return resolve_int(node.args[0], consts)
        return None
    if isinstance(node, ast.BinOp):
        l = resolve_int(node.left, consts)
        r = resolve_int(node.right, consts)
        if l is None or r is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return l + r
            if isinstance(node.op, ast.Sub):
                return l - r
            if isinstance(node.op, ast.Mult):
                return l * r
            if isinstance(node.op, ast.FloorDiv):
                return l // r
            if isinstance(node.op, ast.Mod):
                return l % r
            if isinstance(node.op, ast.Pow):
                # bound the result: resolve_int runs over every
                # module-level assignment of every linted file, and an
                # unbounded `l ** r` on a typo'd exponent chain would
                # materialize astronomically large ints and stall the
                # lint gate
                if r < 0 or r > 64 or abs(l) > 1 << 20:
                    return None
                return l ** r
        except (ZeroDivisionError, OverflowError):
            return None
    return None


def resolve_shape(node, consts):
    """Tuple of ints for a literal Tuple/List shape, else None (None
    also when ANY element is unresolvable — rules must skip, not
    guess)."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in node.elts:
        v = resolve_int(e, consts)
        if v is None:
            return None
        dims.append(v)
    return tuple(dims)


def module_int_consts(tree):
    """Module-level `NAME = <int>` bindings (incl. np.int32(0)-style),
    resolved to a fixpoint so consts may reference earlier consts."""
    consts = {}
    for _ in range(3):  # tiny fixpoint: const chains are shallow
        changed = False
        for st in tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                if name in consts:
                    continue
                v = resolve_int(st.value, consts)
                if v is not None:
                    consts[name] = v
                    changed = True
        if not changed:
            break
    return consts


def dtype_name(node):
    """'float32' from jnp.float32 / np.float32 / 'float32' / "float32"."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    d = dotted_name(node)
    if d is not None:
        return d.split(".")[-1]
    return None


def local_functions(tree):
    """name -> FunctionDef for every def in the file (any nesting);
    later defs win, mirroring runtime rebinding."""
    fns = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns[n.name] = n
    return fns
