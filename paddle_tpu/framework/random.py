"""Global RNG state.

Parity: reference `paddle.seed` / generator state
(`python/paddle/framework/random.py`, `phi/core/generator.h`).

TPU-native design: the state is a JAX PRNG key held in a mutable cell. Every
random op splits the key (counter-based threefry — deterministic and
reproducible across hosts). The cell implements the get_state/set_state
protocol so `paddle_tpu.jit.to_static` can functionalize it: inside a traced
train step the key is threaded as an input/output, giving *different* dropout
masks per step under one compiled executable (the reference achieves the same
with stateful cuRAND generators; the functional key is the XLA-friendly way).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["seed", "get_rng_state", "set_rng_state", "default_rng", "RNGState",
           "rng_key"]


class RNGState:
    """A splittable PRNG stream with named sub-streams (for TP determinism).

    Key creation is lazy: materializing a PRNG key initializes the XLA
    backend, and `import paddle_tpu` must stay backend-free so
    `jax.distributed.initialize` (init_parallel_env) can run first in
    multi-host processes."""

    def __init__(self, seed_val: int = 0):
        self._seed = int(seed_val)
        self._key = None

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    @key.setter
    def key(self, value):
        self._key = value

    def seed(self, seed_val: int):
        self._seed = int(seed_val)
        self._key = None

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub

    # --- state protocol (used by to_static functionalization) ---
    def get_state(self):
        return self.key

    def set_state(self, state):
        self.key = state


_global = RNGState(0)


def default_rng() -> RNGState:
    return _global


def seed(seed_val: int):
    """Parity: paddle.seed."""
    _global.seed(int(seed_val))
    # keep TP rng-state trackers in sync lazily (they re-derive from base seed)
    return _global


def rng_key():
    """Split and return a fresh subkey from the global stream."""
    return _global.next_key()


def get_rng_state():
    return _global.get_state()


def set_rng_state(state):
    _global.set_state(state)
