"""Framework-level utilities: RNG state, save/load."""
from .io import save, load  # noqa: F401
from .random import seed, get_rng_state, set_rng_state, default_rng  # noqa: F401
