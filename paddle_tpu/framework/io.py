"""paddle.save / paddle.load.

Parity: reference `python/paddle/framework/io.py` — pickle-based state
serialization for Tensors / state dicts / nested containers.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__pt_tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__pt_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj.get("stop_gradient", True),
                       name=obj.get("name", ""))
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_saveable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        raw = pickle.load(f)
    return _from_saveable(raw, return_numpy=configs.get("return_numpy", False))
