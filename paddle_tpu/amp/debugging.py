"""amp.debugging — per-op dtype statistics for mixed-precision debugging.

Parity: reference `python/paddle/amp/debugging.py` —
enable/disable_operator_stats_collection, collect_operator_stats context
(prints the op calls grouped by dtype so low-precision leakage is visible),
and the TensorCheckerConfig/enable_tensor_checker nan/inf scan (here the
framework-wide FLAGS_check_nan_inf path already wired into the dispatch
funnel).

TPU-native: the dispatch funnel is the single choke point every op passes
through, so stats collection is one hook there — no per-kernel
instrumentation.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker"]

_stats_lock = threading.Lock()
_collecting = [False]
# op name -> dtype -> call count
_op_stats: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))


def _record(name, out_leaves):
    """Called from the dispatch funnel when collection is on."""
    with _stats_lock:
        for o in out_leaves:
            dt = str(getattr(o, "dtype", "other"))
            _op_stats[name][dt] += 1


def _is_collecting():
    return _collecting[0]


def enable_operator_stats_collection():
    """Parity: amp/debugging.py enable_operator_stats_collection."""
    with _stats_lock:
        _op_stats.clear()
    _collecting[0] = True


def disable_operator_stats_collection():
    """Stop collecting and print the dtype table (reference behavior)."""
    _collecting[0] = False
    _print_table()


def _print_table():
    dtypes = ["float32", "float16", "bfloat16", "other"]
    width = 40 + 12 * len(dtypes)
    print("-" * width)
    print(f"{'op':<40}" + "".join(f"{d:>12}" for d in dtypes))
    print("=" * width)
    with _stats_lock:
        for name in sorted(_op_stats):
            counts = _op_stats[name]
            row = {d: 0 for d in dtypes}
            for dt, n in counts.items():
                row[dt if dt in row else "other"] += n
            print(f"{name[:39]:<40}" +
                  "".join(f"{row[d]:>12}" for d in dtypes))
    print("-" * width)


class collect_operator_stats:
    """Context form (parity: amp/debugging.py collect_operator_stats)."""

    def __enter__(self):
        enable_operator_stats_collection()
        return self

    def __exit__(self, *exc):
        disable_operator_stats_collection()
        return False


def operator_stats():
    """Programmatic access to the collected table (copy)."""
    with _stats_lock:
        return {k: dict(v) for k, v in _op_stats.items()}


class TensorCheckerConfig:
    """Parity: amp/debugging.py TensorCheckerConfig — configures the
    nan/inf scan (enable_check_nan_inf path in the dispatch funnel)."""

    def __init__(self, enable=True, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None, **kw):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(config: TensorCheckerConfig):
    from ..utils.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": bool(config.enable)})


def disable_tensor_checker():
    from ..utils.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": False})


class DebugMode:
    """Parity: amp.debugging.DebugMode (tensor-checker verbosity levels)."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_AND_ABORT = 4
    DUMP_ALL = 5


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Parity: amp.debugging.check_numerics — count/flag nan/inf in one
    tensor; returns (stats, values) like the reference kernel's outputs:
    stats = [num_nan, num_inf, num_zero], values = [max, min, mean]."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..ops.dispatch import apply_op

    def _f(a):
        af = a.astype(jnp.float32)
        stats = jnp.stack([jnp.isnan(af).sum(), jnp.isinf(af).sum(),
                           (af == 0).sum()]).astype(jnp.int64)
        finite = jnp.where(jnp.isfinite(af), af, 0.0)
        values = jnp.stack([finite.max(), finite.min(), finite.mean()])
        return stats, values

    return apply_op("check_numerics", _f, tensor)


def check_layer_numerics(func):
    """Parity: amp.debugging.check_layer_numerics — decorator for a
    Layer.forward that validates every input/output tensor."""
    import functools
    from ..core.tensor import Tensor

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        import numpy as np
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                stats, _ = check_numerics(a)
                s = np.asarray(stats._data)
                if s[0] or s[1]:
                    raise RuntimeError(
                        f"{type(self).__name__} input {i}: {int(s[0])} nan "
                        f"/ {int(s[1])} inf values")
        out = func(self, *args, **kwargs)
        if isinstance(out, Tensor):
            stats, _ = check_numerics(out)
            s = np.asarray(stats._data)
            if s[0] or s[1]:
                raise RuntimeError(
                    f"{type(self).__name__} output: {int(s[0])} nan / "
                    f"{int(s[1])} inf values")
        return out
    return wrapper


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1.0, dump_all_module_name=None):
    """Parity: amp.debugging.compare_accuracy — diff two operator-stats
    dumps (produced by collect_operator_stats runs) into a CSV report."""
    import csv
    import json
    import os

    def load(path):
        with open(path) as f:
            return json.load(f)

    a, b = load(dump_path), load(another_dump_path)
    keys = sorted(set(a) | set(b))
    os.makedirs(os.path.dirname(output_filename) or ".", exist_ok=True)
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["op", "run1", "run2", "equal"])
        for k in keys:
            w.writerow([k, a.get(k), b.get(k), a.get(k) == b.get(k)])
    return output_filename


__all__ += ["DebugMode", "check_numerics", "check_layer_numerics",
            "compare_accuracy"]
