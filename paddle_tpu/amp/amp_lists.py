"""Per-op AMP allow/deny lists.

Parity: reference `python/paddle/amp/amp_lists.py` (WHITE_LIST ops run in
fp16/bf16, BLACK_LIST ops stay fp32, the rest follow inputs).
"""

# ops that benefit from half precision (MXU-bound)
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "sdpa", "addmm",
}

# numerically sensitive ops that must stay fp32
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "c_softmax_with_cross_entropy", "layer_norm", "group_norm", "instance_norm",
    "batch_norm", "rms_norm", "reduce_mean", "reduce_sum", "linspace", "erf",
    "erfinv", "pow", "logsumexp", "norm", "var", "std", "renorm", "cumsum",
    "cumprod", "prod", "nll_loss", "bce", "bce_logits", "kl_div", "mse_loss",
    "l1_loss", "smooth_l1",
}

EXTRA_BLACK_LIST = set()


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST) | EXTRA_BLACK_LIST
