"""Dynamic loss scaling.

Parity: reference `python/paddle/amp/grad_scaler.py:657,62` (GradScaler /
AmpScaler): scale loss, unscale grads, skip step on inf/nan, grow/shrink the
scale. On TPU with bf16 this is typically disabled (bf16 has fp32's range);
kept for fp16 parity and API compatibility.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler", "OptimizerState"]


class OptimizerState(enum.Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._state = OptimizerState.INIT

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad_buffer is not None:
                g = p._grad_buffer.astype(jnp.float32) * inv
                if bool(jnp.any(~jnp.isfinite(g))):
                    found = True
                p._grad_buffer = g.astype(p._grad_buffer.dtype)
        self._found_inf = found
        self._state = OptimizerState.UNSCALED

    def minimize(self, optimizer, loss, *args, **kwargs):
        loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._state == OptimizerState.INIT:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._state = OptimizerState.STEPPED

    def update(self):
        if not self._enable or not self._dynamic:
            self._state = OptimizerState.INIT
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._state = OptimizerState.INIT

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, new_scale):
        self._scale = float(new_scale)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps,
                "enable": self._enable,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._enable = state.get("enable", self._enable)
        self._dynamic = state.get("use_dynamic_loss_scaling", self._dynamic)


class GradScaler(AmpScaler):
    """Parity: paddle.amp.GradScaler."""
