"""AMP: autocast + GradScaler.

Parity: reference `python/paddle/amp/` — `auto_cast` (O1 per-op allow/deny
lists, O2 whole-model cast), `GradScaler` dynamic loss scaling, master
weights (held by optimizers via multi_precision).

TPU-native notes: bf16 is the native half type (no loss scaling needed —
GradScaler becomes a near-no-op passthrough when dtype=bfloat16, matching
the reference's bf16 path); fp16 scaling is kept for parity.
"""
from .auto_cast import auto_cast, amp_guard, decorate, is_auto_cast_enabled, get_amp_dtype  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler, OptimizerState  # noqa: F401
from . import amp_lists  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler"]

from . import debugging  # noqa: F401


def is_float16_supported(device=None):
    """float16 compute support probe (parity: paddle.amp). TPUs compute
    in bfloat16; fp16 works via XLA but without MXU benefit."""
    import jax
    return jax.default_backend() != "tpu"


def is_bfloat16_supported(device=None):
    """bfloat16 is the native TPU matmul dtype; CPU supports it too."""
    return True
