"""Autocast context.

Parity: reference `python/paddle/amp/auto_cast.py:462,1029` (amp_guard +
decorate). Level O1 casts per-op via the allow/deny lists at the dispatch
funnel (ops/dispatch.apply_op consults this module); O2 casts model
parameters to the amp dtype up front (decorate) with fp32 master weights in
the optimizer.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..core.dtype import convert_dtype

__all__ = ["auto_cast", "amp_guard", "decorate", "is_auto_cast_enabled",
           "get_amp_dtype", "amp_dtype_for_op"]


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    return _state.dtype if _state.enabled else None


def amp_dtype_for_op(op_name: str):
    """Called by ops.dispatch.apply_op: returns the dtype this op's float
    inputs should be cast to under the active autocast, or None."""
    if not _state.enabled:
        return None
    from . import amp_lists
    name = op_name.lower()
    if name in _state.custom_black or name in amp_lists.black_list():
        return jnp.float32
    if _state.level == "O2":
        return _state.dtype
    if name in _state.custom_white or name in amp_lists.white_list():
        return _state.dtype
    return None


class auto_cast:
    """Context manager / decorator. Parity: paddle.amp.auto_cast."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype)
        self.white = set(custom_white_list or ())
        self.black = set(custom_black_list or ())
        self._saved = None

    def __enter__(self):
        self._saved = (_state.enabled, _state.dtype, _state.level,
                       _state.custom_white, _state.custom_black)
        _state.enabled = bool(self.enable)
        _state.dtype = self.dtype
        _state.level = self.level
        _state.custom_white = self.white
        _state.custom_black = self.black
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = self._saved
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with auto_cast(self.enable, self.white, self.black, self.level,
                           self.dtype):
                return fn(*a, **k)
        return wrapper


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2 decoration: cast model params to amp dtype; optimizer keeps fp32
    master weights. Parity: paddle.amp.decorate."""
    d = convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        excluded = excluded_layers or ()
        from ..nn.layer.norm import _BatchNormBase, LayerNorm
        default_excluded = (_BatchNormBase, LayerNorm)
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, default_excluded) or \
                        any(isinstance(layer, e) for e in
                            (excluded if isinstance(excluded, (list, tuple)) else (excluded,))):
                    continue
                for _, p in layer._parameters.items():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        p._data = p._data.astype(d)
    if optimizers is None:
        return models if single_model else model_list
    single_opt = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single_opt else list(optimizers)
    for opt in opt_list:
        opt._multi_precision = True
    return (models if single_model else model_list,
            optimizers if single_opt else opt_list)
