"""paddle.quantization.observers — module-path parity (reference
quantization/observers/); implementations live in the package root."""
from . import (AbsmaxObserver, BaseObserver,  # noqa: F401
               AbsMaxChannelWiseWeightObserver)

__all__ = ["AbsmaxObserver", "AbsMaxChannelWiseWeightObserver",
           "BaseObserver"]




class GroupWiseWeightObserver(BaseObserver):
    """Parity: observers.GroupWiseWeightObserver — absmax per group of
    `group_size` input channels (the int4 grouped-quant observer)."""

    def __init__(self, quant_bits=4, group_size=128, **kwargs):
        super().__init__()
        self.bits = quant_bits
        self.group_size = group_size
        self._scales = None

    def forward(self, x):
        import jax.numpy as jnp
        a = x._data if hasattr(x, "_data") else x
        g = self.group_size
        k = a.shape[0]
        pad = (-k) % g
        ap = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        grouped = ap.reshape(ap.shape[0] // g, g, *ap.shape[1:])
        qmax = 2 ** (self.bits - 1) - 1
        self._scales = jnp.max(jnp.abs(grouped), axis=1) / qmax
        return x

    def scales(self):
        from ..core.tensor import Tensor
        return Tensor(self._scales)


__all__ += ["GroupWiseWeightObserver"]
