"""paddle.quantization.quanters — module-path parity (reference
quantization/quanters/)."""
from . import (BaseQuanter, FakeQuanterWithAbsMaxObserver,  # noqa: F401
               QuanterFactory, quanter)

__all__ = ["BaseQuanter", "FakeQuanterWithAbsMaxObserver",
           "QuanterFactory", "quanter"]
