"""Quantization: PTQ observers + QAT fake-quant + config/factory.

Parity: reference `python/paddle/quantization/` — QuantConfig
(config.py: add_layer_config/add_type_config/add_name_config),
QuanterFactory (factory.py), BaseObserver (base_observer.py:23),
AbsmaxObserver (observers/abs_max.py), FakeQuanterWithAbsMaxObserver
(quanters/abs_max.py), PTQ (ptq.py:29) and QAT (qat.py:27) flows with
ObserveWrapper (wrapper.py) and quantize/convert (quantize.py).

TPU-native: fake-quant uses the straight-through estimator expressed as
``x + stop_grad(dq(q(x)) - x)`` — XLA folds it into the surrounding
computation; converted inference layers hold int8 weights + scales and
run through nn.quant.weight_only_linear (Pallas dequant-matmul).
"""
from __future__ import annotations

import copy
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op

__all__ = ["BaseQuanter", "BaseObserver", "AbsmaxObserver",
           "AbsMaxChannelWiseWeightObserver",
           "FakeQuanterWithAbsMaxObserver", "QuanterFactory", "quanter",
           "SingleLayerConfig", "QuantConfig", "PTQ", "QAT",
           "ObserveWrapper", "QuantedLinear"]


def _fake_quant(x, scale, qmax=127.0):
    """Quantize-dequantize with straight-through gradients."""
    s = jnp.maximum(scale, 1e-10)
    dq = jnp.clip(jnp.round(x / s), -qmax, qmax) * s
    return x + jax.lax.stop_gradient(dq - x)


class BaseQuanter(Layer):
    """Parity: base_quanter.py. Produces quant params after observation."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def bit_length(self):
        return 8

    def quant_axis(self):
        return None


class BaseObserver(BaseQuanter):
    """Parity: base_observer.py:23 — records statistics in forward, yields
    thresholds via cal_thresholds()."""

    def cal_thresholds(self):
        pass


class AbsmaxObserver(BaseObserver):
    """Per-tensor absmax activation observer (observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self._absmax = 0.0

    def forward(self, x):
        val = float(np.asarray(jnp.max(jnp.abs(x._data))))
        self._absmax = max(self._absmax, val)
        return x

    def cal_thresholds(self):
        self._scale = self._absmax / (2 ** (self._quant_bits - 1) - 1)

    def scales(self):
        self.cal_thresholds()
        return self._scale

    def bit_length(self):
        return self._quant_bits


class AbsMaxChannelWiseWeightObserver(BaseObserver):
    """Per-output-channel weight observer (observers/ + groupwise.py)."""

    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = quant_axis
        self._absmax = None

    def forward(self, x):
        w = x._data
        axes = tuple(i for i in range(w.ndim) if i != (self._axis % w.ndim))
        cur = np.asarray(jnp.max(jnp.abs(w), axis=axes))
        self._absmax = cur if self._absmax is None else \
            np.maximum(self._absmax, cur)
        return x

    def scales(self):
        return self._absmax / (2 ** (self._quant_bits - 1) - 1)

    def quant_axis(self):
        return self._axis

    def bit_length(self):
        return self._quant_bits


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT fake-quant with a moving-average absmax (quanters/abs_max.py)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype=None, name=None):
        super().__init__()
        self._rate = moving_rate
        self._bits = bit_length
        self._state = None

    def forward(self, x):
        cur = float(np.asarray(jnp.max(jnp.abs(jax.lax.stop_gradient(
            x._data)))))
        self._state = cur if self._state is None else \
            self._rate * self._state + (1 - self._rate) * cur
        scale = jnp.float32(self._state / (2 ** (self._bits - 1) - 1))
        return apply_op("fake_quant",
                        lambda a: _fake_quant(a, scale,
                                              2 ** (self._bits - 1) - 1), x)

    def scales(self):
        return self._state / (2 ** (self._bits - 1) - 1)

    def bit_length(self):
        return self._bits


class QuanterFactory:
    """Partial-bound quanter constructor (factory.py)."""

    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._args = args
        self._kwargs = kwargs

    def _instance(self, layer=None):
        return self._cls(*self._args, **self._kwargs)

    def __repr__(self):
        return f"QuanterFactory({self._cls.__name__})"


def quanter(name):
    """Decorator registering a quanter class and returning a factory maker
    (parity: factory.py quanter decorator)."""
    def deco(cls):
        def make(*args, **kwargs):
            return QuanterFactory(cls, *args, **kwargs)
        globals()[name] = make
        return cls
    return deco


class SingleLayerConfig:
    """Parity: config.py SingleLayerConfig."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Parity: config.py QuantConfig — per-layer / per-type / per-name
    quanter configuration."""

    def __init__(self, activation=None, weight=None):
        self._global = SingleLayerConfig(activation, weight)
        self._layer_configs = []     # (layer_obj, cfg)
        self._type_configs = []      # (layer_cls, cfg)
        self._name_configs = []      # (name, cfg)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs.append(
                (l, SingleLayerConfig(activation, weight)))

    def _remap_layers(self, old_root, new_root):
        """Layer configs are identity-keyed; quantize() deepcopies the
        model, so retarget each config onto the structurally corresponding
        layer of the copy."""
        mapping = {}
        for (_n1, old), (_n2, new) in zip(
                old_root.named_sublayers(include_self=True),
                new_root.named_sublayers(include_self=True)):
            mapping[id(old)] = new
        self._layer_configs = [(mapping.get(id(l), l), cfg)
                               for l, cfg in self._layer_configs]

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_configs.append(
                (t, SingleLayerConfig(activation, weight)))

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        for n in names:
            self._name_configs.append(
                (n, SingleLayerConfig(activation, weight)))

    def _config_for(self, name, layer):
        for l, cfg in self._layer_configs:
            if l is layer:
                return cfg
        for n, cfg in self._name_configs:
            # `name` is the fully qualified path from the model root
            if n == name or name.endswith("." + n):
                return cfg
        for t, cfg in self._type_configs:
            if isinstance(layer, t):
                return cfg
        if self._global.activation is not None or \
                self._global.weight is not None:
            if isinstance(layer, _linear_types()):
                return self._global
        return None


def _linear_types():
    """Layer types the global default config applies to: plain Linear and
    the TP mpu linears (so the ERNIE/Llama ladder models quantize)."""
    from ..nn import Linear
    from ..distributed.fleet.mpu import (ColumnParallelLinear,
                                         RowParallelLinear)
    return (Linear, ColumnParallelLinear, RowParallelLinear)


class ObserveWrapper(Layer):
    """Observed layer: activation observer on input, weight observer fed the
    weight (parity: wrapper.py ObserveWrapper)."""

    def __init__(self, observed, cfg: SingleLayerConfig):
        super().__init__()
        self._observed = observed
        self._act = cfg.activation._instance() if cfg.activation else None
        self._weight_ob = cfg.weight._instance() if cfg.weight else None

    def forward(self, *args, **kwargs):
        if self._act is not None and args:
            args = (self._act(args[0]),) + args[1:]
        if self._weight_ob is not None and hasattr(self._observed, "weight"):
            self._weight_ob(self._observed.weight)
        return self._observed(*args, **kwargs)


class QuantedLinear(Layer):
    """Converted inference layer: int8 weight + per-channel scale through
    nn.quant.weight_only_linear (the Pallas dequant-matmul path).

    weight_scales: calibrated per-channel scales from the weight observer
    (falls back to fresh absmax — identical for absmax observers, distinct
    for moving-average/custom ones). act_scale is carried for serving-side
    activation quantization."""

    def __init__(self, linear, weight_scales=None, act_scale=None):
        super().__init__()
        import jax.numpy as jnp
        from ..nn import quant as Q
        w = linear.weight
        if weight_scales is not None:
            s = jnp.maximum(jnp.asarray(weight_scales, jnp.float32), 1e-10)
            if s.ndim == 0:        # per-tensor observer -> broadcast
                s = jnp.full((w.shape[-1],), s)
            q = jnp.clip(jnp.round(w._data / s[None, :]), -127, 127)
            self.qweight = Tensor(q.astype(jnp.int8))
            self.weight_scale = Tensor(s)
        else:
            qw, scale = Q.weight_quantize(w, algo="weight_only_int8")
            self.qweight = qw
            self.weight_scale = scale
        self.act_scale = act_scale
        self.bias = getattr(linear, "bias", None)

    def forward(self, x):
        from ..nn import quant as Q
        return Q.weight_only_linear(x, self.qweight, self.bias,
                                    self.weight_scale, "int8")


class Quantization:
    """Parity: quantize.py Quantization base."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def _wrap(self, model, prefix=""):
        for name, child in list(model._sub_layers.items()):
            qualified = f"{prefix}.{name}" if prefix else name
            cfg = self._config._config_for(qualified, child)
            if cfg is not None:
                model._sub_layers[name] = self._make_wrapper(child, cfg)
            else:
                self._wrap(child, qualified)
        return model

    def convert(self, model, inplace=False, remain_weight=False):
        """Replace observed/fake-quant layers with quantized inference
        layers (int8 weights + scales)."""
        if not inplace:
            model = copy.deepcopy(model)
        self._convert(model)
        return model

    def _convert(self, model):
        for name, child in list(model._sub_layers.items()):
            target = getattr(child, "_observed", None)
            if isinstance(child, ObserveWrapper) and \
                    isinstance(target, _linear_types()):
                try:  # uncalibrated observers fall back to fresh absmax
                    ws = child._weight_ob.scales() \
                        if child._weight_ob is not None else None
                except Exception:
                    ws = None
                try:
                    act = child._act.scales() if child._act is not None \
                        else None
                except Exception:
                    act = None
                model._sub_layers[name] = QuantedLinear(
                    target, weight_scales=ws, act_scale=act)
            elif isinstance(child, ObserveWrapper):
                model._sub_layers[name] = target
            else:
                self._convert(child)


class PTQ(Quantization):
    """Post-training quantization flow (ptq.py:29): quantize() wraps
    matching layers with observers; run calibration batches; convert()."""

    def quantize(self, model, inplace=False):
        if not inplace:
            new = copy.deepcopy(model)
            self._config._remap_layers(model, new)
            model = new
        return self._wrap(model)

    def _make_wrapper(self, layer, cfg):
        return ObserveWrapper(layer, cfg)


class _QATWrapper(Layer):
    """Fake-quant on weight + activation in forward (STE grads) —
    nn.quant.qat.QuantedLinear's role."""

    def __init__(self, observed, cfg):
        super().__init__()
        self._observed = observed
        self._act_q = cfg.activation._instance() if cfg.activation else None
        self._weight_q = cfg.weight._instance() if cfg.weight else None

    def forward(self, *args, **kwargs):
        if self._act_q is not None and args:
            args = (self._act_q(args[0]),) + args[1:]
        if self._weight_q is not None and hasattr(self._observed, "weight"):
            w = self._observed.weight
            orig = w._data
            fq = self._weight_q(w)
            w._data = fq._data
            try:
                return self._observed(*args, **kwargs)
            finally:
                w._data = orig
        return self._observed(*args, **kwargs)

    @property
    def _observed_target(self):
        return self._observed


class QAT(Quantization):
    """Quantization-aware training flow (qat.py:27)."""

    def quantize(self, model, inplace=False):
        if not inplace:
            new = copy.deepcopy(model)
            self._config._remap_layers(model, new)
            model = new
        return self._wrap(model)

    def _make_wrapper(self, layer, cfg):
        return _QATWrapper(layer, cfg)

    def _convert(self, model):
        for name, child in list(model._sub_layers.items()):
            target = getattr(child, "_observed", None)
            if isinstance(child, _QATWrapper) and \
                    isinstance(target, _linear_types()):
                model._sub_layers[name] = QuantedLinear(target)
            elif isinstance(child, _QATWrapper):
                model._sub_layers[name] = target
            else:
                self._convert(child)


# module-path parity with reference quantization/{observers,quanters}/
from . import observers  # noqa: F401,E402
from . import quanters  # noqa: F401,E402
__all__ += ["observers", "quanters"]
