"""python -m paddle_tpu.distributed.launch (placeholder CLI)."""


def launch():
    raise NotImplementedError("launch CLI lands with multi-host support")


if __name__ == "__main__":
    launch()
