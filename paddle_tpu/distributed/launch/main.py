"""python -m paddle_tpu.distributed.launch — multi-process / multi-host
launcher.

Parity: reference launch stack — `python/paddle/distributed/launch/
controllers/controller.py:28-192` (Controller spawning per-rank Containers,
watch loop), `controllers/collective.py:22` (rank env construction), and
the fake-multinode pattern (`test/collective/test_communication_api_base.py:
62-76`: N launchers on localhost sharing one --master).

TPU-native: one process per host is the norm (a process owns all local
chips); rendezvous is jax.distributed.initialize (PJRT coordination
service) — the launcher's job is rank bookkeeping, environment setup,
child supervision, and the TCPStore KV for launch-level coordination.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of nodes (hosts) in the job")
    p.add_argument("--node_rank", "--rank", type=int, dest="node_rank",
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")),
                   help="rank of this node in [0, nnodes)")
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator endpoint host:port (required when "
                        "nnodes > 1)")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.environ.get("PADDLE_NPROC_PER_NODE", "1")),
                   help="processes per node (1 per TPU host is the norm)")
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids for this node (informational "
                        "on TPU; one process owns all local chips)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="per-rank stdout/stderr capture directory")
    p.add_argument("--run_mode", type=str, default="collective",
                   help="collective (default); ps/rpc modes are not "
                        "supported on TPU")
    p.add_argument("training_script", type=str,
                   help="script to run (or module with -m inside the script)")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _rank_env(args, local_rank):
    """Per-process environment (parity: CollectiveController.build_pod
    rank env, `launch/controllers/collective.py:22`)."""
    nproc = args.nproc_per_node
    world = args.nnodes * nproc
    rank = args.node_rank * nproc + local_rank
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_NODE_RANK": str(args.node_rank),
        "PADDLE_RANK_IN_NODE": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(nproc),
        "PADDLE_WORLD_SIZE": str(world),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        # The TCPStore for host-side p2p (dist.send/recv) needs a port
        # DISTINCT from the jax coordinator; export the sibling port so
        # workers get a working mailbox out of the box. port+1 is the
        # only deterministic choice every NODE can agree on without
        # coordination; a clash surfaces as a clear TCPStore bind error
        # and the user overrides by exporting PADDLE_P2P_STORE.
        from ..env import _split_endpoint
        try:
            host, port = _split_endpoint(args.master)
            if port + 1 <= 65535:
                env.setdefault("PADDLE_P2P_STORE", f"{host}:{port + 1}")
        except ValueError:
            pass
    if args.devices is not None:
        env["PADDLE_DEVICES"] = args.devices
    return env


def launch(argv=None):
    """Spawn nproc_per_node child processes with rank env and supervise
    them. Returns the first non-zero child exit code (0 on full success).
    Parity: ControllerBase.run/watch (`controllers/controller.py:28-192`)."""
    args = _parse_args(argv)
    if args.nnodes > 1 and not args.master:
        raise SystemExit("--master host:port is required when --nnodes > 1")
    if args.nproc_per_node > 1 and not args.master:
        # single-node multi-process still needs a coordinator so the
        # children call jax.distributed.initialize (reference launcher
        # auto-assigns a localhost master)
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        args.master = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
    if args.run_mode != "collective":
        raise SystemExit(f"run_mode {args.run_mode!r} is not supported; "
                         "only 'collective' exists on the TPU backend")

    script_cmd = [sys.executable, "-u", args.training_script]
    script_cmd += list(args.training_script_args)
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local_rank in range(args.nproc_per_node):
        env = _rank_env(args, local_rank)
        stdout = stderr = None
        if args.log_dir:
            rank = env["PADDLE_TRAINER_ID"]
            stdout = open(os.path.join(args.log_dir,
                                       f"workerlog.{rank}"), "wb")
            stderr = subprocess.STDOUT
        procs.append(subprocess.Popen(script_cmd, env=env, stdout=stdout,
                                      stderr=stderr))

    # watch loop: first failure tears the pod down (controller.py watch)
    exit_code = 0
    try:
        pending = {p.pid: p for p in procs}
        while pending:
            for pid, p in list(pending.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del pending[pid]
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    for q in pending.values():
                        q.terminate()
            time.sleep(0.1)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        exit_code = exit_code or 130
    finally:
        deadline = time.monotonic() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
    return exit_code


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
