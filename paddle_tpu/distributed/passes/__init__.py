"""paddle.distributed.passes — pass registry (module-path parity).

Parity: reference `python/paddle/distributed/passes/__init__.py`
(new_pass + PassManager over ~40 program passes). On the TPU build the
program transformations those passes perform are owned by XLA/GSPMD or
by the schedule builders; new_pass returns a descriptor that maps a
known pass name onto the owning subsystem, and raises (rather than
silently no-ops) for passes with no TPU analog.
"""
from __future__ import annotations

__all__ = ["new_pass", "PassManager", "PassContext"]

# pass name -> (owner, how the capability is reached in this build)
_KNOWN = {
    "pipeline_scheduler_FThenB": (
        "distributed.pipeline",
        "DistributedStrategy.pipeline_configs['schedule_mode']='FThenB'"),
    "pipeline_scheduler_1F1B": (
        "distributed.pipeline", "schedule_mode='1F1B'"),
    "pipeline_scheduler_VPP": (
        "distributed.pipeline", "interleaved schedule: n_virtual>1"),
    "pipeline_scheduler_ZBH1": (
        "distributed.fleet_executor",
        "ZeroBubbleRunner / schedule_mode='ZBH1'"),
    "auto_parallel_amp": ("amp", "paddle.amp.auto_cast / strategy.amp"),
    "auto_parallel_fp16": ("amp", "auto_cast(level='O2')"),
    "auto_parallel_recompute": (
        "fleet.utils.recompute", "jax.checkpoint per stage"),
    "auto_parallel_sharding": (
        "distributed.sharding", "ZeRO placement policies"),
    "auto_parallel_gradient_merge_pass": (
        "fleet.HybridParallelOptimizer", "strategy.gradient_merge"),
    "fuse_gemm_epilogue": ("XLA", "fused automatically by XLA"),
    "fused_attention": ("kernels.flash_attention", "Pallas flash"),
    "fuse_optimizer": ("XLA", "optimizer update fuses under to_static"),
}


class PassContext:
    def __init__(self):
        self.attrs = {}


class _Pass:
    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = dict(attrs or {})
        self._info = _KNOWN.get(name)

    def apply(self, main_programs=None, startup_programs=None,
              context=None):
        if self._info is None:
            raise NotImplementedError(
                f"pass {self.name!r} has no TPU analog in this build")
        owner, how = self._info
        raise NotImplementedError(
            f"pass {self.name!r} is not applied as a program rewrite on "
            f"the TPU build — the capability is owned by {owner} ({how})")

    def __repr__(self):
        return f"Pass({self.name})"


def new_pass(name, pass_attrs=None):
    return _Pass(name, pass_attrs)


class PassManager:
    def __init__(self, passes=None):
        self.passes = list(passes or [])

    def append(self, p):
        self.passes.append(p)

    def apply(self, main_programs=None, startup_programs=None):
        for p in self.passes:
            p.apply(main_programs, startup_programs, PassContext())
