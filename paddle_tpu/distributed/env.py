"""Distributed environment/bootstrap.

Parity: reference `python/paddle/distributed/parallel.py` env handling
(PADDLE_TRAINER_* vars + TCPStore rendezvous). TPU-native: rendezvous is
jax.distributed.initialize (PJRT coordination service) — the TCPStore role;
single-process multi-device is the common TPU mode, where world_size is the
process count (1) but the device mesh spans all chips.
"""
from __future__ import annotations

import os

import jax

__all__ = ["get_rank", "get_world_size", "init_parallel_env",
           "is_initialized", "ParallelEnv"]

_initialized = [False]


def init_parallel_env(strategy=None):
    """Parity: paddle.distributed.init_parallel_env. Multi-host: reads
    coordinator address from env (PADDLE_MASTER or JAX_COORDINATOR) and
    calls jax.distributed.initialize."""
    if _initialized[0]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("JAX_COORDINATOR")
    nnodes = int(os.environ.get("PADDLE_NNODES", "1"))
    if coord and nnodes > 1:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("PADDLE_TRAINERS_NUM", nnodes)),
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    _initialized[0] = True
    return ParallelEnv()


def is_initialized():
    return _initialized[0]


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", "0"))
