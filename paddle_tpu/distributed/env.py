"""Distributed environment/bootstrap.

Parity: reference `python/paddle/distributed/parallel.py` env handling
(PADDLE_TRAINER_* vars + TCPStore rendezvous). TPU-native: rendezvous is
jax.distributed.initialize (PJRT coordination service) — the TCPStore role;
single-process multi-device is the common TPU mode, where world_size is the
process count (1) but the device mesh spans all chips.
"""
from __future__ import annotations

import os

import jax

__all__ = ["get_rank", "get_world_size", "init_parallel_env",
           "is_initialized", "ParallelEnv", "create_store",
           "release_store", "barrier_store"]

_initialized = [False]
_store = [None]    # default store (first created)
_stores = {}       # endpoint -> store


def _split_endpoint(ep, default_host="127.0.0.1"):
    """'host:port' -> (host, int port); bare ':port'/'port' get the
    default host. Shared by create_store and the launcher's
    PADDLE_P2P_STORE derivation."""
    host, _, port = ep.rpartition(":")
    return host or default_host, int(port)


def create_store(endpoint=None, rank=None, timeout_ms=120000):
    """Native TCPStore rendezvous KV (parity: reference
    `phi/core/distributed/store/tcp_store.cc`, created in
    `python/paddle/distributed/parallel.py:1134-1143`). On TPU the PJRT
    coordination service does collective bootstrap; this store carries the
    remaining roles: launch/elastic KV, barriers, user rendezvous.

    Process-wide registry keyed by endpoint: a second call with the same
    endpoint returns the existing store; a DIFFERENT endpoint creates a
    second store (the launcher's eager PADDLE_P2P_STORE mailbox and a
    user-chosen rendezvous store legitimately coexist). `_store[0]`
    remains the default store — the first one created — for consumers
    that don't name an endpoint."""
    from .._native import TCPStore
    # PADDLE_P2P_STORE (exported by the launcher) takes precedence:
    # PADDLE_MASTER is the jax coordinator's endpoint, whose PORT the
    # coordination service owns — binding a TCPStore there clashes.
    # PADDLE_MASTER stays as a last-resort compat default for callers
    # outside any launcher.
    endpoint = endpoint or os.environ.get("PADDLE_P2P_STORE") \
        or os.environ.get("MASTER_ENDPOINT") \
        or os.environ.get("PADDLE_MASTER", "127.0.0.1:29600")
    if endpoint in _stores:
        return _stores[endpoint]
    host, port = _split_endpoint(endpoint)
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")) if rank is None \
        else rank
    store = TCPStore(host, port, is_master=(rank == 0),
                     timeout_ms=timeout_ms)
    try:
        store._pt_endpoint = endpoint
    except AttributeError:  # native type: wrap in a proxy attribute holder
        store = _StoreProxy(store, endpoint)
    _stores[endpoint] = store
    if _store[0] is None:
        _store[0] = store
    return store


def release_store(endpoint):
    """Drop `endpoint` from the process-wide registry so the native
    store can close when its last reference dies (the cross-process
    fleet binds one ephemeral-port store per supervisor — a long-lived
    process must be able to release them; ISSUE 14). Returns whether
    an entry was removed. The default-store slot moves to any other
    registered store."""
    store = _stores.pop(endpoint, None)
    if store is None:
        return False
    if _store[0] is store:
        _store[0] = next(iter(_stores.values()), None)
    return True


class _StoreProxy:
    def __init__(self, store, endpoint):
        self._store = store
        self._pt_endpoint = endpoint

    def __getattr__(self, name):
        return getattr(self._store, name)


def barrier_store(store, world_size, prefix="barrier", timeout=120):
    """Store-based reusable process barrier (used by launch/elastic):
    the k-th barrier on a prefix completes when the shared counter reaches
    k*world_size, so repeated barriers on one prefix keep synchronising
    (every rank must call it the same number of times)."""
    import struct
    import time
    n = store.add(f"{prefix}/arrived", 1)
    target = ((n + world_size - 1) // world_size) * world_size
    deadline = time.monotonic() + timeout
    while n < target:
        got = store.get(f"{prefix}/arrived", wait=False)
        if got is not None and len(got) == 8:
            n = struct.unpack("<q", got)[0]
        if n >= target:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(f"barrier timed out at {n}/{target}")
        time.sleep(0.01)


def init_parallel_env(strategy=None):
    """Parity: paddle.distributed.init_parallel_env. Multi-host/-process:
    reads the coordinator address from env (PADDLE_MASTER or
    JAX_COORDINATOR, set by paddle_tpu.distributed.launch) and calls
    jax.distributed.initialize — the PJRT coordination service plays the
    reference TCPStore+NCCL-bootstrap role. Must run before any other jax
    backend use in the process."""
    if _initialized[0]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("JAX_COORDINATOR")
    world = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               os.environ.get("PADDLE_NNODES", "1")))
    if coord and world > 1:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
        # eagerly stand up the p2p/rpc TCPStore when the launcher
        # exported one: rank 0 must BIND the mailbox port even if it
        # never performs p2p itself (otherwise ranks 1..n-1 would spin
        # against a port nobody serves until the connect timeout)
        if os.environ.get("PADDLE_P2P_STORE"):
            try:
                create_store(os.environ["PADDLE_P2P_STORE"])
            except Exception:
                pass  # p2p stays usable via explicit create_store
    _initialized[0] = True
    return ParallelEnv()


def is_initialized():
    return _initialized[0]


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", "0"))
