"""Semi-auto parallel API: shard_tensor / reshard / shard_layer /
shard_optimizer / dtensor_from_local.

Parity: reference `python/paddle/distributed/auto_parallel/api.py`
(shard_tensor:204, reshard:726, shard_layer:827, shard_optimizer:1002,
dtensor_from_local:640) and the C++ DistTensor + reshard function matrix
(`phi/core/distributed/auto_parallel/reshard/`).

TPU-native: a "DistTensor" is a paddle_tpu Tensor whose jax.Array carries a
NamedSharding over the ProcessMesh's jax Mesh — placement conversion
(the r/s/p matrix) is `jax.device_put` to the new sharding, which XLA lowers
to the same collectives the reference's reshard functions issue explicitly
(s→r all_gather, r→s slice, s→s' all_to_all, p→r psum, p→s reduce_scatter).
Partial is represented stacked-along-axis (value = sum over that axis),
since a jax.Array cannot carry pending-reduction state.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .placement_type import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["shard_tensor", "reshard", "dtensor_from_local", "dtensor_to_local",
           "shard_layer", "shard_optimizer", "to_static", "unshard_dtensor",
           "placements_to_spec", "DistAttr", "moe_global_mesh_tensor",
           "moe_sub_mesh_tensors"]


def placements_to_spec(placements: Sequence[Placement], ndim: int) -> P:
    """placements (one per mesh dim) -> PartitionSpec (one entry per tensor
    dim). Parity role: TensorDistAttr dims_mapping."""
    entries: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.get_dim()
            cur = entries[d]
            name = mesh_dim  # resolved to actual axis name by caller
            if cur is None:
                entries[d] = name
            elif isinstance(cur, tuple):
                entries[d] = cur + (name,)
            else:
                entries[d] = (cur, name)
    return entries


def _build_sharding(mesh: ProcessMesh, placements, ndim):
    jmesh = mesh.jax_mesh
    entries = placements_to_spec(placements, ndim)
    names = mesh.dim_names

    def to_names(e):
        if e is None:
            return None
        if isinstance(e, tuple):
            return tuple(names[i] for i in e)
        return names[e]
    spec = P(*[to_names(e) for e in entries])
    return NamedSharding(jmesh, spec)


class DistAttr:
    """Parity: TensorDistAttr (mesh + placements view)."""

    def __init__(self, mesh, placements):
        self.process_mesh = mesh
        self.placements = list(placements)


def _attach(t: Tensor, mesh, placements):
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """Parity: dist.shard_tensor. Returns a Tensor whose array is laid out
    per `placements` on the mesh."""
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(np.asarray(data)))
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("shard_tensor from a global tensor cannot produce "
                         "Partial; use dtensor_from_local.")
    sharding = _build_sharding(mesh, placements, t._data.ndim)
    arr = jax.device_put(t._data, sharding)
    out = Tensor(arr, stop_gradient=t.stop_gradient if stop_gradient is None
                 else stop_gradient, name=t.name)
    out._is_param = t._is_param
    return _attach(out, mesh, placements)


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements):
    """Parity: dist.reshard — the full r/s/p conversion matrix."""
    cur_pl = getattr(dist_tensor, "placements", None)
    cur_mesh = getattr(dist_tensor, "process_mesh", None)
    has_partial_src = cur_pl is not None and any(
        isinstance(p, Partial) for p in cur_pl)
    wants_partial = any(isinstance(p, Partial) for p in placements)

    if has_partial_src:
        # stacked representation: data shape (axis_size, *logical) sharded on
        # the partial mesh axis; reduce then continue.
        pidx = next(i for i, p in enumerate(cur_pl) if isinstance(p, Partial))
        reduced = jnp.sum(dist_tensor._data, axis=0) \
            if cur_pl[pidx].reduce_type == "sum" else \
            jnp.max(dist_tensor._data, axis=0)
        base = Tensor(reduced, stop_gradient=dist_tensor.stop_gradient)
        new_pl = [Replicate() if isinstance(p, Partial) else p for p in cur_pl]
        base = shard_tensor(base, cur_mesh or mesh, new_pl)
        return reshard(base, mesh, placements)

    if wants_partial:
        raise ValueError("reshard to Partial is not supported (Partial only "
                         "arises from local construction).")

    sharding = _build_sharding(mesh, placements, dist_tensor._data.ndim)
    arr = jax.device_put(dist_tensor._data, sharding)
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient,
                 name=dist_tensor.name)
    out._is_param = dist_tensor._is_param
    return _attach(out, mesh, placements)


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements):
    """Parity: dist.dtensor_from_local (api.py:640). In single-process SPMD,
    `local_tensor` may be a list of per-rank locals (test/bootstrap path) or
    one local replicated across the mesh."""
    jmesh = mesh.jax_mesh
    locals_list = local_tensor if isinstance(local_tensor, (list, tuple)) \
        else [local_tensor] * mesh.size
    arrs = [l._data if isinstance(l, Tensor) else jnp.asarray(l)
            for l in locals_list]

    partial_dims = [i for i, p in enumerate(placements) if isinstance(p, Partial)]
    if partial_dims:
        # stacked representation (value = sum over the partial axis)
        pdim = partial_dims[0]
        stacked = jnp.stack(arrs, axis=0)
        ax_name = mesh.dim_names[pdim]
        sharding = NamedSharding(jmesh, P(ax_name))
        arr = jax.device_put(stacked, sharding)
        out = Tensor(arr)
        return _attach(out, mesh, list(placements))

    # assemble the global array from locals
    shard_dims = {i: p.get_dim() for i, p in enumerate(placements)
                  if isinstance(p, Shard)}
    global_shape = list(arrs[0].shape)
    for mesh_dim, tdim in shard_dims.items():
        global_shape[tdim] *= mesh.shape[mesh_dim]
    sharding = _build_sharding(mesh, placements, arrs[0].ndim)
    devices = list(jmesh.devices.reshape(-1))
    mesh_shape = mesh.shape

    def local_for_device(flat_idx):
        coords = np.unravel_index(flat_idx, mesh_shape)
        return arrs[flat_idx % len(arrs)], coords

    singles = []
    for i, d in enumerate(devices):
        a, _ = local_for_device(i)
        singles.append(jax.device_put(a, d))
    arr = jax.make_array_from_single_device_arrays(tuple(global_shape),
                                                   sharding, singles)
    out = Tensor(arr)
    return _attach(out, mesh, list(placements))


def dtensor_to_local(dist_tensor, mesh=None, placements=None):
    """The local shard for this process (single-process: addressable shard 0)."""
    shards = dist_tensor._data.addressable_shards
    return Tensor(shards[0].data)


def _normalize_mesh_dim(mesh: ProcessMesh, local_mesh_dim: int) -> int:
    ndim = mesh.ndim
    if not -ndim <= local_mesh_dim < ndim:
        raise ValueError(
            f"local_mesh_dim {local_mesh_dim} out of range for mesh with "
            f"{ndim} dims")
    return local_mesh_dim % ndim


def _sub_meshes(mesh: ProcessMesh, local_mesh_dim: int):
    """Split `mesh` along `local_mesh_dim` into one sub-mesh per index
    (e.g. a [ep, mp] mesh at dim 0 -> one [mp] mesh per expert group)."""
    arr = np.asarray(mesh.process_ids).reshape(mesh.shape)
    names = [n for i, n in enumerate(mesh.dim_names)
             if i != local_mesh_dim]
    return [ProcessMesh(np.take(arr, idx, axis=local_mesh_dim), names)
            for idx in range(mesh.shape[local_mesh_dim])]


def moe_global_mesh_tensor(local_tensor_list, mesh: ProcessMesh, placements,
                          local_mesh_dim: int = -1):
    """Parity: dist.moe_global_mesh_tensor (reference
    `python/paddle/distributed/auto_parallel/api.py:462`, there named
    over `_moe_global_mesh_tensor`). Build ONE dist tensor on the
    global `mesh` from per-sub-mesh locals — the MoE pattern: each
    expert group owns a local tensor on its sub-mesh (the global mesh
    sliced along `local_mesh_dim`, conventionally the expert-parallel
    axis); the returned global view concatenates them along the tensor
    dim `placements[local_mesh_dim]` shards (or validates equality for
    Replicate).

    TPU-native: the locals are (sub-mesh-)jax.Arrays; the global view
    is one device_put to the full-mesh NamedSharding — GSPMD then owns
    the layout exactly as for any shard_tensor result.
    """
    dim = _normalize_mesh_dim(mesh, local_mesh_dim)
    n_sub = mesh.shape[dim]
    if len(local_tensor_list) != n_sub:
        raise ValueError(
            f"need one local tensor per sub-mesh: got "
            f"{len(local_tensor_list)} for mesh dim of size {n_sub}")
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
            for t in local_tensor_list]
    pl = placements[dim]
    if isinstance(pl, Shard):
        global_data = jnp.concatenate(arrs, axis=pl.get_dim())
    elif isinstance(pl, Replicate):
        for i, a in enumerate(arrs[1:], 1):
            if a.shape != arrs[0].shape or not bool(
                    jnp.array_equal(a, arrs[0])):
                raise ValueError(
                    f"Replicate on mesh dim {dim} requires identical "
                    f"locals; sub-mesh {i} differs from sub-mesh 0")
        global_data = arrs[0]
    else:
        raise ValueError(
            "moe_global_mesh_tensor supports Shard/Replicate on the "
            f"local mesh dim; got {pl!r} (Partial locals carry pending "
            "reductions a stacked jax.Array cannot represent here)")
    return shard_tensor(Tensor(global_data), mesh, placements)


def moe_sub_mesh_tensors(dist_tensor, global_mesh: ProcessMesh = None,
                         local_mesh_dim: int = -1,
                         global_placements=None):
    """Parity: dist.moe_sub_mesh_tensors (reference api.py:603) — the
    inverse of moe_global_mesh_tensor: split a global dist tensor into
    one local dist tensor per sub-mesh along `local_mesh_dim`. Shard on
    the local mesh dim splits the tensor dim it names; Replicate hands
    every sub-mesh the full view."""
    mesh = global_mesh or getattr(dist_tensor, "process_mesh", None)
    if mesh is None:
        raise ValueError("dist_tensor carries no mesh and none was given")
    placements = global_placements or \
        getattr(dist_tensor, "placements", None)
    if placements is None:
        raise ValueError("dist_tensor carries no placements and none "
                         "were given")
    dim = _normalize_mesh_dim(mesh, local_mesh_dim)
    pl = placements[dim]
    local_placements = [p for i, p in enumerate(placements) if i != dim]
    data = dist_tensor._data
    n_sub = mesh.shape[dim]
    if isinstance(pl, Shard):
        td = pl.get_dim()
        if data.shape[td] % n_sub:
            raise ValueError(
                f"tensor dim {td} of size {data.shape[td]} does not "
                f"split over {n_sub} sub-meshes")
        chunks = jnp.split(data, n_sub, axis=td)
    elif isinstance(pl, Replicate):
        chunks = [data] * n_sub
    else:
        raise ValueError(
            "moe_sub_mesh_tensors supports Shard/Replicate on the local "
            f"mesh dim; got {pl!r}")
    return [shard_tensor(Tensor(c), sub, local_placements)
            for c, sub in zip(chunks, _sub_meshes(mesh, dim))]


def unshard_dtensor(dist_tensor):
    """Gather to a replicated dense tensor. Parity: dist.unshard_dtensor."""
    mesh = getattr(dist_tensor, "process_mesh", None)
    if mesh is None:
        return dist_tensor
    return reshard(dist_tensor, mesh,
                   [Replicate()] * len(mesh.shape))


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn: Callable = None,
                input_fn=None, output_fn=None):
    """Parity: dist.shard_layer (api.py:827): apply shard_fn(name, layer,
    mesh) over sublayers to place their parameters. The returned layer's
    forward runs under spmd_propagation(mesh): every op consults the SPMD
    rule registry and pins rule-known intermediate placements with
    sharding constraints (GSPMD fills the rest) — the wiring of the
    reference's InferSpmd dist branch (VERDICT r2 missing #3)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in list(sublayer._parameters.items()):
                if p is not None:
                    sharded = shard_tensor(p, mesh,
                                           [Replicate()] * len(mesh.shape))
                    p._data = sharded._data
                    _attach(p, mesh, sharded.placements)
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    from .propagation import spmd_propagation
    orig_forward = layer.forward

    def _propagating_forward(*a, **k):
        with spmd_propagation(process_mesh):
            return orig_forward(*a, **k)

    layer.forward = _propagating_forward
    layer._spmd_mesh = process_mesh
    return layer


class _ShardOptimizer:
    """Parity: dist.shard_optimizer (+ ShardingStage1/2/3 placement policies,
    api.py:1002,1306-1504). Wraps an optimizer so accumulators created for a
    parameter inherit (or override via shard_fn) that parameter's placement."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        if self._shard_fn is not None:
            for name, slot in self._inner._accumulators.items():
                for idx, arr in slot.items():
                    p = self._inner._parameter_list[idx]
                    mesh = getattr(p, "process_mesh", None)
                    if mesh is None:
                        continue
                    new = self._shard_fn(name, p, Tensor(arr))
                    if new is not None:
                        slot[idx] = new._data if isinstance(new, Tensor) else new
        else:
            # default: accumulators co-located with the parameter's sharding
            for name, slot in self._inner._accumulators.items():
                for idx, arr in slot.items():
                    p = self._inner._parameter_list[idx]
                    if isinstance(p._data, jax.Array) and hasattr(arr, "sharding"):
                        if arr.sharding != p._data.sharding and \
                                arr.shape == p._data.shape:
                            slot[idx] = jax.device_put(arr, p._data.sharding)


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)


class DistModel:
    """Parity: dist.DistModel (auto_parallel/api.py) — the compiled
    distributed train/eval callable dist.to_static returns. The step is
    jit-compiled over the already-sharded parameters and runs under
    spmd_propagation when a mesh is discoverable (layer._spmd_mesh from
    shard_layer, or the first parameter's process_mesh) so the SPMD rule
    registry pins intermediate placements inside the program."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        import contextlib
        from ...jit import to_static as jit_to_static
        from .propagation import spmd_propagation

        self.network = layer
        self._loss = loss
        self._opt = optimizer
        self._strategy = strategy
        self._mode = "train"

        mesh = getattr(layer, "_spmd_mesh", None)
        if mesh is None:
            for p in layer.parameters():
                m = getattr(p, "process_mesh", None)
                if m is not None:
                    mesh = m
                    break

        # `mode` rides as a leading STATIC argument so train vs eval get
        # distinct guard-cache entries (a closure read would freeze the
        # trace-time mode into the compiled program)
        def step_fn(mode, *batch):
            ctx = (spmd_propagation(mesh) if mesh is not None
                   else contextlib.nullcontext())
            with ctx:
                out = layer(*batch[:-1])
                l = loss(out, batch[-1]) if loss is not None else out
                if optimizer is not None and mode == "train":
                    l.backward()
                    optimizer.step()
                    optimizer.clear_grad()
            return l

        self._step = jit_to_static(
            step_fn, state_objects=[layer] +
            ([optimizer] if optimizer else []))

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def __call__(self, *batch):
        return self._step(self._mode, *batch)

    def state_dict(self, mode="all"):
        return self.network.state_dict()

    def dist_main_program(self, mode=None):
        return self._step


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Parity: dist.to_static -> DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy)
