from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401
from .placement_type import Placement, Shard, Replicate, Partial  # noqa: F401
from .api import (  # noqa: F401
    shard_tensor, reshard, dtensor_from_local, dtensor_to_local, shard_layer,
    shard_optimizer, to_static, unshard_dtensor, DistAttr,
)

from . import spmd_rules  # noqa: F401
from .propagation import spmd_propagation, propagation_mesh  # noqa: F401
