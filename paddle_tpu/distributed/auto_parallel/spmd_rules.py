"""Per-op SPMD sharding-propagation rules.

Parity: reference `paddle/phi/infermeta/spmd_rules/` (111 files, registry
`rules.h`): each op declares how input shardings propagate to outputs
(`MatmulInferSpmd`, elementwise, embedding, reduction, softmax, ...),
consumed by the generated dist branch (InferSpmd -> reshard -> local
kernel, `phi/api/generator/dist_api_gen.py:49-110`).

TPU-native: GSPMD performs whole-program propagation inside XLA, so these
rules are not on the execution path of every op. They exist as the
queryable registry the reference exposes — used by shard_layer-style
planners to choose placements ahead of compilation, by tests documenting
expected propagation, and as explicit constraints (`apply_rule`) when
GSPMD's choice should be pinned. Specs are `jax.sharding.PartitionSpec`s;
`None` entries mean replicated along that dim; the reference's `Partial`
state maps to GSPMD's implicit pending-reduction the rules mark in
`partial_axes`.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

__all__ = ["register_spmd_rule", "get_spmd_rule", "infer_spmd",
           "SpmdResult"]

_RULES: Dict[str, Callable] = {}


class SpmdResult:
    """(input specs as the rule demands them, output specs, axes whose
    reduction is pending — the reference's Partial placements)."""

    def __init__(self, in_specs, out_specs, partial_axes=()):
        self.in_specs = list(in_specs)
        self.out_specs = out_specs if isinstance(out_specs, list) \
            else [out_specs]
        self.partial_axes = tuple(partial_axes)

    def __repr__(self):
        return (f"SpmdResult(in={self.in_specs}, out={self.out_specs}, "
                f"partial={self.partial_axes})")


def register_spmd_rule(name):
    def deco(fn):
        for n in ([name] if isinstance(name, str) else name):
            _RULES[n] = fn
        return fn
    return deco


def get_spmd_rule(name: str) -> Callable:
    """Parity: SpmdRuleFactory lookup (spmd_rules/rules.h); falls back to
    the replicated rule like VariadicReplicatedInferSpmdDynamic."""
    return _RULES.get(name, _replicated_rule)


def infer_spmd(name: str, *in_specs, **attrs) -> SpmdResult:
    return get_spmd_rule(name)(*in_specs, **attrs)


def _ent(spec, i):
    entries = tuple(spec) if spec is not None else ()
    # negative i = a broadcast dim the shorter operand doesn't have:
    # replicated, NOT python wrap-around
    return entries[i] if 0 <= i < len(entries) else None


def _pad(spec, ndim):
    entries = list(tuple(spec) if spec is not None else ())
    entries += [None] * (ndim - len(entries))
    return entries


# ------------------------------------------------------------------ rules
def _replicated_rule(*in_specs, **attrs):
    """Fallback: everything replicated (spmd_rules replicated.cc)."""
    return SpmdResult([P() for _ in in_specs], P())


@register_spmd_rule(["add", "subtract", "multiply", "divide", "maximum",
                     "minimum", "pow", "elementwise"])
def elementwise_rule(*in_specs, **attrs):
    """Broadcast elementwise: merge shardings dim-by-dim from the right;
    conflicting meshes axes fall back to replicated on that dim
    (spmd_rules elementwise.cc). `None` specs (unknown placement) are
    treated as fully replicated."""
    ndim = max((len(tuple(s or ())) for s in in_specs), default=0)
    out = []
    for i in range(ndim):
        picks = {e for s in in_specs
                 for e in [_ent(s, len(tuple(s or ())) - ndim + i)]
                 if e is not None}
        out.append(picks.pop() if len(picks) == 1 else None)
    spec = P(*out)
    return SpmdResult(list(in_specs), spec)


@register_spmd_rule(["matmul", "mm", "bmm"])
def matmul_rule(x_spec, y_spec, trans_x=False, trans_y=False, **attrs):
    """MatmulInferSpmd (spmd_rules/matmul.h:25): batch dims merge, the
    contracted dim's sharding induces a Partial output, row/col shardings
    pass through."""
    xs, ys = tuple(x_spec or ()), tuple(y_spec or ())
    xm = xs[-2] if len(xs) >= 2 and not trans_x else \
        (xs[-1] if trans_x and len(xs) >= 1 else None)
    xk = xs[-1] if len(xs) >= 1 and not trans_x else \
        (xs[-2] if trans_x and len(xs) >= 2 else None)
    yk = ys[-2] if len(ys) >= 2 and not trans_y else \
        (ys[-1] if trans_y and len(ys) >= 1 else None)
    yn = ys[-1] if len(ys) >= 1 and not trans_y else \
        (ys[-2] if trans_y and len(ys) >= 2 else None)
    batch = list(xs[:-2]) if len(xs) > 2 else []
    contracted = xk if xk is not None else yk
    partial = (contracted,) if (xk is not None and xk == yk) else ()
    out = P(*(batch + [xm, yn]))
    return SpmdResult([x_spec, y_spec], out, partial_axes=partial)


@register_spmd_rule(["embedding", "c_embedding"])
def embedding_rule(ids_spec, weight_spec, **attrs):
    """spmd_rules/embedding.cc: vocab-dim sharding yields a Partial output
    (the vocab-parallel allreduce); ids sharding passes through."""
    vocab_axis = _ent(weight_spec, 0)
    emb_axis = _ent(weight_spec, 1)
    out = P(*(list(tuple(ids_spec or ())) + [emb_axis]))
    partial = (vocab_axis,) if vocab_axis is not None else ()
    return SpmdResult([ids_spec, weight_spec], out, partial_axes=partial)


@register_spmd_rule(["softmax", "log_softmax"])
def softmax_rule(x_spec, axis=-1, **attrs):
    """spmd_rules/softmax.cc: the softmax dim must be unsharded; all other
    dims pass through."""
    xs = list(tuple(x_spec or ()))
    if xs:
        xs[axis if axis >= 0 else len(xs) + axis] = None
    spec = P(*xs)
    return SpmdResult([spec], spec)


@register_spmd_rule(["cross_entropy_with_softmax", "parallel_cross_entropy"])
def cross_entropy_rule(logits_spec, label_spec, **attrs):
    """spmd_rules/cross_entropy_with_softmax.cc: class-dim sharding is the
    vocab-parallel case — loss output is Partial over that axis."""
    cls_axis = _ent(logits_spec, len(tuple(logits_spec or ())) - 1)
    out = P(*tuple(logits_spec or ())[:-1])
    partial = (cls_axis,) if cls_axis is not None else ()
    return SpmdResult([logits_spec, label_spec], out, partial_axes=partial)


@register_spmd_rule(["layer_norm", "rms_norm"])
def norm_rule(x_spec, *param_specs, **attrs):
    """spmd_rules/layer_norm.cc: normalized (last) dim must be replicated;
    leading dims pass through; params replicated."""
    xs = _pad(x_spec, len(tuple(x_spec or ())))
    if xs:
        xs[-1] = None
    spec = P(*xs)
    return SpmdResult([spec] + [P() for _ in param_specs], spec)


@register_spmd_rule(["reduction", "sum", "mean", "max", "min"])
def reduction_rule(x_spec, axis=None, keepdim=False, **attrs):
    """spmd_rules reduction: reducing a sharded dim yields Partial over
    its axis; kept dims pass through."""
    xs = list(tuple(x_spec or ()))
    if axis is None:
        axes = list(range(len(xs)))
    else:
        axes = [a if a >= 0 else len(xs) + a
                for a in (axis if isinstance(axis, (list, tuple)) else [axis])]
    partial = tuple(xs[a] for a in axes if a < len(xs) and xs[a] is not None)
    out = []
    for i, e in enumerate(xs):
        if i in axes:
            if keepdim:
                out.append(None)
        else:
            out.append(e)
    return SpmdResult([x_spec], P(*out), partial_axes=partial)


@register_spmd_rule(["transpose", "t"])
def transpose_rule(x_spec, perm=None, **attrs):
    xs = list(tuple(x_spec or ()))
    if perm is None:
        perm = list(reversed(range(len(xs))))
    out = [xs[p] if p < len(xs) else None for p in perm]
    return SpmdResult([x_spec], P(*out))


@register_spmd_rule("concat")
def concat_rule(*in_specs, axis=0, **attrs):
    """spmd_rules/concat.cc: the concat dim must be replicated; others
    merge like elementwise."""
    merged = elementwise_rule(*in_specs).out_specs[0]
    out = list(tuple(merged or ()))
    if out and axis < len(out):
        out[axis] = None
    spec = P(*out)
    return SpmdResult(list(in_specs), spec)


@register_spmd_rule("stack")
def stack_rule(*in_specs, axis=0, **attrs):
    """spmd_rules/stack.cc: inputs merge elementwise, the new stacked
    dim is replicated (each input lands whole on its index)."""
    merged = list(tuple(elementwise_rule(*in_specs).out_specs[0] or ()))
    a = axis if axis >= 0 else len(merged) + 1 + axis
    a = max(0, min(a, len(merged)))
    out = merged[:a] + [None] + merged[a:]
    spec = P(*out)
    return SpmdResult(list(in_specs), spec)


@register_spmd_rule("split")
def split_rule(x_spec, axis=0, **attrs):
    xs = list(tuple(x_spec or ()))
    if xs and axis < len(xs):
        xs[axis] = None
    spec = P(*xs)
    return SpmdResult([spec], spec)


@register_spmd_rule("unbind")
def unbind_rule(x_spec, axis=0, **attrs):
    """Like split, but the unbound dim disappears from each output."""
    xs = list(tuple(x_spec or ()))
    a = axis if axis >= 0 else len(xs) + axis
    out = [e for i, e in enumerate(xs) if i != a]
    spec = P(*out)
    return SpmdResult([x_spec], spec)


@register_spmd_rule(["flash_attention", "sdpa"])
def flash_attention_rule(q_spec, k_spec, v_spec, **attrs):
    """spmd_rules/flash_attention.cc: batch and head dims propagate; the
    sequence dim may stay sharded (context parallel); head_dim replicated."""
    qs = _pad(q_spec, 4)
    out = P(qs[0], qs[1], qs[2], None)
    return SpmdResult([q_spec, k_spec, v_spec], out)


@register_spmd_rule(["reshape", "flatten"])
def reshape_rule(x_spec, **attrs):
    """spmd_rules/reshape.cc via dim_trans: without the shape pair the
    only always-safe propagation keeps the leading dim's sharding."""
    lead = _ent(x_spec, 0)
    return SpmdResult([x_spec], P(lead))


@register_spmd_rule("default_data_parallel")
def default_data_parallel_rule(*in_specs, mesh_axis="data", **attrs):
    """spmd_rules/default_data_parallel.cc: batch dim sharded over the
    data axis for every input/output."""
    outs = [P(mesh_axis) for _ in in_specs]
    return SpmdResult(outs, P(mesh_axis))


# -- expanded set (VERDICT r2 missing #3: grow toward rules.h's ~50 ops) ----

@register_spmd_rule([
    # elementwise-unary: placement passes through untouched
    # (spmd_rules/elementwise.cc ElementwiseUnaryInferSpmd)
    "cast", "exp", "log", "log2", "log10", "log1p", "expm1", "sin", "cos",
    "tan", "tanh", "sigmoid", "relu", "relu6", "gelu", "silu", "swish",
    "sqrt", "rsqrt", "square", "abs", "neg", "negative", "sign", "floor",
    "ceil", "round", "erf", "erfinv", "logit", "clip", "scale", "clone",
    "tril", "triu", "dropout", "leaky_relu", "elu", "selu", "celu",
    "hardswish", "hardsigmoid", "hardtanh", "softplus", "softsign", "mish",
    "label_smooth", "nan_to_num",
])
def unary_rule(x_spec, *rest, **attrs):
    return SpmdResult([x_spec] + [P() for _ in rest], x_spec)


@register_spmd_rule(["where", "masked_fill", "lerp", "fused_dropout_add"])
def ternary_elementwise_rule(*in_specs, **attrs):
    """where/masked_fill/lerp: broadcast elementwise over all operands
    (spmd_rules/elementwise.cc ternary entry points)."""
    return elementwise_rule(*in_specs, **attrs)


@register_spmd_rule(["linear", "fused_linear"])
def linear_rule(x_spec, w_spec, *bias, **attrs):
    """x @ W (+ b), W layout (in, out) — MatmulInferSpmd with the bias
    broadcast on the out dim (spmd_rules/matmul.h + fused_linear)."""
    base = matmul_rule(x_spec, w_spec, **attrs)
    return SpmdResult(base.in_specs + [P() for _ in bias],
                      base.out_specs, partial_axes=base.partial_axes)


@register_spmd_rule(["rope", "rope_slice",
                     "fused_rotary_position_embedding"])
def rope_rule(x_spec, *rest, **attrs):
    """Rotary embedding is positionwise on (B, S, H, D): placement passes
    through (spmd_rules/fused_rope.cc)."""
    return SpmdResult([x_spec] + [P() for _ in rest], x_spec)


@register_spmd_rule(["swiglu", "fused_bias_act"])
def swiglu_rule(*in_specs, **attrs):
    """Gated activation: elementwise over the gate/value operands
    (spmd_rules/fused_bias_act — er, the swiglu entry in rules.h)."""
    return elementwise_rule(*in_specs, **attrs)


@register_spmd_rule("repeat_kv")
def repeat_kv_rule(x_spec, *rest, **attrs):
    """GQA head replication keeps (B, S, H, D) placement; the head dim's
    sharding stays valid because repeats are along heads."""
    return SpmdResult([x_spec] + [P() for _ in rest], x_spec)


@register_spmd_rule(["gather_nd", "index_sample", "take_along_axis"])
def gather_like_rule(x_spec, idx_spec, **attrs):
    """Conservative gather family: batch dims follow the index operand,
    gathered dims replicated (spmd_rules/gather.cc's safe default)."""
    return SpmdResult([x_spec, idx_spec], idx_spec if idx_spec else P())


@register_spmd_rule(["cross_entropy", "nll_loss"])
def plain_ce_rule(logits_spec, label_spec, *rest, **attrs):
    """Unfused CE: batch dims pass through, class dim must produce a
    Partial if sharded (cross_entropy_with_softmax.cc)."""
    base = cross_entropy_rule(logits_spec, label_spec, **attrs)
    return SpmdResult(base.in_specs + [P() for _ in rest],
                      base.out_specs, partial_axes=base.partial_axes)


# -- round-4 growth toward rules.h's full registry (VERDICT r3 item 2) -----

# prod/amax/amin share the reduction shape rule; their non-sum combine is
# why partial_axes makes the hook abstain rather than pin.
register_spmd_rule(["prod", "amax", "amin"])(reduction_rule)


def _norm_axes(axes, ndim):
    if axes is None:
        return list(range(ndim))
    axes = axes if isinstance(axes, (list, tuple)) else [axes]
    return [int(a) if int(a) >= 0 else ndim + int(a) for a in axes]


@register_spmd_rule("slice")
def slice_rule(x_spec, axes=(), **attrs):
    """spmd_rules/slice.cc SliceInferSpmd: sliced dims lose their
    sharding (a partial extent cannot stay block-distributed); untouched
    dims pass through."""
    xs = list(tuple(x_spec or ()))
    for a in _norm_axes(axes, len(xs)):
        if a < len(xs):
            xs[a] = None
    spec = P(*xs)
    return SpmdResult([spec], spec)


@register_spmd_rule("strided_slice")
def strided_slice_rule(x_spec, axes=(), **attrs):
    return slice_rule(x_spec, axes=axes, **attrs)


@register_spmd_rule("pad")
def pad_rule(x_spec, padded_dims=None, **attrs):
    """spmd_rules/pad.cc: padded dims must be replicated. `padded_dims`
    is the resolved list of dim indices that receive nonzero padding
    (the call site resolves paddle's two pad-list layouts); unpadded
    dims pass through."""
    xs = list(tuple(x_spec or ()))
    if padded_dims is None:
        spec = P()
        return SpmdResult([spec], spec)
    for d in padded_dims:
        if 0 <= int(d) < len(xs):
            xs[int(d)] = None
    spec = P(*xs)
    return SpmdResult([spec], spec)


@register_spmd_rule("tile")
def tile_rule(x_spec, repeat_times=(), x_ndim=None, **attrs):
    """spmd_rules/tile.cc: any repeated dim is replicated (tiling a
    block-sharded dim would interleave shards); reps align to the
    right like broadcasting, new leading dims replicated. `x_ndim`
    (threaded by the call site) pads a truncated left-aligned spec to
    the tensor rank so right-alignment lands on the real dims."""
    xs = _pad(x_spec, x_ndim if x_ndim is not None
              else len(tuple(x_spec or ())))
    reps = list(repeat_times)
    ndim_out = max(len(xs), len(reps))
    out = [None] * ndim_out
    for i in range(ndim_out):
        xi = len(xs) - ndim_out + i
        ri = len(reps) - ndim_out + i
        rep = reps[ri] if ri >= 0 else 1
        if xi >= 0 and rep == 1:
            out[i] = xs[xi]
    spec = P(*out)
    return SpmdResult([x_spec], spec)


@register_spmd_rule(["expand", "broadcast_to", "expand_as"])
def expand_rule(x_spec, shape=(), x_ndim=None, **attrs):
    """spmd_rules/expand_as.cc: existing dims keep their sharding (a
    size-1 dim is never sharded so broadcast is local); new leading dims
    replicated. The input spec is padded to `x_ndim` (left-aligned
    PartitionSpec semantics) before right-aligning against `shape`."""
    xs = _pad(x_spec, x_ndim if x_ndim is not None
              else len(tuple(x_spec or ())))
    ndim_out = max(len(shape), len(xs)) if shape else len(xs)
    out = [None] * (ndim_out - len(xs)) + xs
    spec = P(*out)
    return SpmdResult([x_spec], spec)


@register_spmd_rule(["cumsum", "cumprod", "cummax", "cummin",
                     "logcumsumexp"])
def cumsum_rule(x_spec, axis=None, **attrs):
    """spmd_rules/cumsum.cc: the scan dim must be replicated (prefix
    dependency crosses shard boundaries); axis=None flattens, so the
    1-D output is replicated."""
    if axis is None:
        spec = P()
        return SpmdResult([x_spec], spec)
    xs = list(tuple(x_spec or ()))
    a = int(axis) if int(axis) >= 0 else len(xs) + int(axis)
    if 0 <= a < len(xs):
        xs[a] = None
    spec = P(*xs)
    return SpmdResult([spec], spec)


@register_spmd_rule("one_hot")
def one_hot_rule(x_spec, **attrs):
    """spmd_rules/one_hot.cc: input dims pass through, the new classes
    dim is replicated."""
    out = list(tuple(x_spec or ())) + [None]
    return SpmdResult([x_spec], P(*out))


@register_spmd_rule("gather")
def gather_axis_rule(x_spec, idx_spec=None, axis=0, **attrs):
    """spmd_rules/gather.cc with a 1-D index: the gathered dim takes the
    index's sharding, other dims pass through."""
    xs = list(tuple(x_spec or ()))
    a = int(axis) if int(axis) >= 0 else len(xs) + int(axis)
    if 0 <= a < len(xs):
        xs[a] = _ent(idx_spec, 0)
    spec = P(*xs)
    return SpmdResult([x_spec, idx_spec], spec)


@register_spmd_rule(["scatter", "scatter_nd_add", "put_along_axis"])
def scatter_rule(x_spec, idx_spec=None, upd_spec=None, **attrs):
    """spmd_rules/scatter.cc conservative default: the scattered (first)
    dim is replicated — indices may target any shard — remaining dims
    keep the destination's sharding."""
    xs = list(tuple(x_spec or ()))
    if xs:
        xs[0] = None
    spec = P(*xs)
    return SpmdResult([spec, idx_spec, upd_spec], spec)


@register_spmd_rule(["p_norm", "logsumexp", "squared_l2_norm", "norm"])
def norm_reduce_rule(x_spec, axis=None, keepdim=False, **attrs):
    """Reduction-shaped but NOT sum-combinable: reducing a sharded dim is
    marked Partial so the dispatch hook abstains and GSPMD emits the
    correct combined collective (spmd_rules/p_norm, logsumexp,
    squared_l2_norm entries in rules.h map Partial with a custom reduce
    type)."""
    base = reduction_rule(x_spec, axis=axis, keepdim=keepdim)
    return SpmdResult(base.in_specs, base.out_specs,
                      partial_axes=base.partial_axes)


@register_spmd_rule(["moe_gate_dispatch", "moe_dispatch"])
def moe_gate_dispatch_rule(x_spec, gate_spec=None, *rest, x_ndim=None,
                           **attrs):
    """rules.h moe_gate_dispatch (paddle_tpu op name: moe_dispatch):
    dispatched output is laid out (experts, capacity, hidden) — expert
    dim takes the gate's expert-dim sharding (the EP axis), capacity
    replicated, hidden follows x's LAST dim (the call site threads
    x_ndim so a truncated left-aligned spec cannot misattribute a
    leading axis to the hidden dim). Secondary outputs (slot indices /
    weights, aux scalar) have different ranks, so the hook's
    rank-validity check leaves them to GSPMD."""
    xs = _pad(x_spec, x_ndim if x_ndim is not None
              else len(tuple(x_spec or ())))
    e_axis = _ent(gate_spec, 1)
    h_axis = xs[-1] if xs else None
    out = P(e_axis, None, h_axis)
    return SpmdResult([x_spec, gate_spec] + [P() for _ in rest], out)


@register_spmd_rule("moe_combine")
def moe_combine_rule(y_spec, info_spec=None, *rest, y_ndim=None, **attrs):
    """rules.h moe_combine: scatter-add expert outputs back to (tokens,
    hidden). The token distribution of the output is NOT derivable from
    the inputs (the second operand is the flat expert-major SLOT index
    array, whose sharding is over slots, not tokens) — so the token dim
    stays unconstrained, hidden follows y's last dim, and a sharded
    expert/slot dim is marked Partial (the scatter-add spans shards:
    the hook abstains and GSPMD inserts the combine)."""
    ys = _pad(y_spec, y_ndim if y_ndim is not None
              else len(tuple(y_spec or ())))
    h_axis = ys[-1] if ys else None
    out = P(None, h_axis)
    partial = tuple(dict.fromkeys(   # unique, order-preserving
        a for a in (ys[0] if ys else None, _ent(info_spec, 0))
        if a is not None))
    return SpmdResult([y_spec, info_spec] + [P() for _ in rest], out,
                      partial_axes=partial)


@register_spmd_rule("squeeze")
def squeeze_rule(x_spec, axis=None, x_ndim=None, **attrs):
    """spmd_rules/squeeze.cc: squeezed (size-1) dims are never sharded;
    their entries drop out, others pass through."""
    nd = x_ndim if x_ndim is not None else len(tuple(x_spec or ()))
    xs = _pad(x_spec, nd)
    if axis is None:
        # without shapes we cannot know which dims are size-1 — abstain
        return SpmdResult([x_spec], P())
    axes = {int(a) % nd for a in
            (axis if isinstance(axis, (list, tuple)) else [axis])}
    out = [e for i, e in enumerate(xs) if i not in axes]
    return SpmdResult([x_spec], P(*out))


@register_spmd_rule("unsqueeze")
def unsqueeze_rule(x_spec, axis=None, x_ndim=None, **attrs):
    """spmd_rules/unsqueeze.cc: new dims enter replicated; existing dims
    keep their sharding."""
    nd = x_ndim if x_ndim is not None else len(tuple(x_spec or ()))
    out = list(_pad(x_spec, nd))
    axes = [int(a) for a in
            (axis if isinstance(axis, (list, tuple)) else [axis or 0])]
    for a in axes:
        a = a if a >= 0 else len(out) + 1 + a
        out.insert(min(max(a, 0), len(out)), None)
    return SpmdResult([x_spec], P(*out))


# argmax/argmin share the reduction shape rule (spmd_rules/argmax.cc);
# a sharded reduced dim is marked Partial — argmax does not combine by
# sum, so the hook abstains and GSPMD handles it.
register_spmd_rule(["argmax", "argmin"])(reduction_rule)


@register_spmd_rule("numel")
def numel_rule(x_spec, **attrs):
    """spmd_rules/numel.cc: scalar count — replicated output (partial if
    the input is sharded). REGISTRY PARITY ONLY: paddle_tpu's numel
    constructs its result without dispatching through apply_op, so this
    rule never fires on the live path — it exists for planners querying
    `infer_spmd` like the reference registry."""
    sharded = [e for e in tuple(x_spec or ()) if e is not None]
    return SpmdResult([x_spec], P(), partial_axes=tuple(sharded))


@register_spmd_rule("nonzero")
def nonzero_rule(x_spec, **attrs):
    """spmd_rules/nonzero.cc: data-dependent output extent — replicated
    input/output. REGISTRY PARITY ONLY (same caveat as numel; and the
    behavior matches the replicated fallback by design)."""
    return SpmdResult([P()], P())


@register_spmd_rule(["full_like", "zeros_like", "ones_like",
                     "empty_like"])
def full_like_rule(x_spec, *rest, **attrs):
    """spmd_rules/full_like.cc: shape follows the input, so its
    placement can too (value is constant everywhere). REGISTRY PARITY
    ONLY: the *_like creation ops build Tensors directly."""
    return SpmdResult([x_spec] + [P() for _ in rest], x_spec)


@register_spmd_rule("add_n")
def add_n_rule(*in_specs, **attrs):
    """spmd_rules/add_n.cc: elementwise sum over the operand list."""
    return elementwise_rule(*in_specs, **attrs)


@register_spmd_rule("conv2d")
def conv2d_rule(x_spec, w_spec, *rest, channel_last=False, **attrs):
    """spmd_rules/conv2d.cc: batch follows x dim 0, out-channel follows
    the weight's dim 0 (jax OIHW layout); spatial dims replicated (halo
    exchange is GSPMD's call); a sharded in-channel is Partial. The
    call site threads `channel_last` so NHWC places the channel on the
    last dim instead of dim 1."""
    xs, ws = _pad(x_spec, 4), _pad(w_spec, 4)
    c_dim = 3 if channel_last else 1
    partial = tuple(e for e in (xs[c_dim], ws[1]) if e is not None)
    out = [None] * 4
    out[0] = xs[0]
    out[c_dim] = ws[0]
    return SpmdResult([x_spec, w_spec] + [P() for _ in rest], P(*out),
                      partial_axes=partial)


@register_spmd_rule(["check_finite_and_unscale", "update_loss_scaling"])
def amp_check_rule(*in_specs, **attrs):
    """rules.h check_finite_and_unscale: each grad keeps its placement;
    found_inf is a replicated scalar the hook leaves alone."""
    return SpmdResult(list(in_specs), list(in_specs))


@register_spmd_rule(["adam", "adamw", "sgd", "momentum", "adam_update",
                     "adamw_update", "sgd_update", "momentum_update"])
def optimizer_update_rule(param_spec, grad_spec=None, *state_specs,
                          **attrs):
    """rules.h optimizer rules (adam_spmd etc.): updated param and every
    moment state inherit the param/grad merged placement — the property
    ZeRO sharding relies on."""
    merged = elementwise_rule(param_spec, grad_spec).out_specs[0] \
        if grad_spec is not None else (param_spec or P())
    return SpmdResult([merged, merged] + [merged for _ in state_specs],
                      merged)
