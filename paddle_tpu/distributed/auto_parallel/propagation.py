"""SPMD rule propagation: wire the per-op rule registry into execution.

Parity: the reference's generated dist branch runs InferSpmd -> reshard ->
local kernel for every eager op on a DistTensor
(`paddle/phi/api/generator/dist_api_gen.py:49-110`, rule set
`paddle/phi/infermeta/spmd_rules/rules.h`). TPU-native wiring (VERDICT r2
missing #3): under `spmd_propagation(mesh)` the dispatch funnel consults
`infer_spmd` after each op and pins the rule's output placement with
`jax.lax.with_sharding_constraint`; ops without a rule (or whose rule
yields a Partial / unknown placement) are left to GSPMD's whole-program
propagation — the constraint set is advisory structure, XLA inserts the
actual collectives.

Specs ride on the framework level: each output Tensor records its
inferred `_spmd_spec`, because inside a jit trace the arrays are tracers
with no observable sharding — exactly why the reference propagates dist
attrs in the framework rather than reading them back from kernels.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .spmd_rules import _RULES, infer_spmd

__all__ = ["spmd_propagation", "propagation_mesh", "maybe_constrain",
           "spec_of", "rule_stats", "reset_rule_stats",
           "rules_prometheus_text"]

_STATE = {"mesh": None}

# Rules whose output is meaningless without these op attributes. Call
# sites thread them through `apply_op(..., op_attrs={...})` (VERDICT r3
# weak #3 — previously attrs lived only in the op closures and every rule
# here was dead); the gate remains so a third-party `apply_op` call that
# omits the attrs falls back to GSPMD instead of pinning a
# default-attr placement.
_ATTR_DEPENDENT = {
    "transpose": ("perm",), "sum": ("axis",), "mean": ("axis",),
    "max": ("axis",), "min": ("axis",), "prod": ("axis",),
    "amax": ("axis",), "amin": ("axis",), "reduction": ("axis",),
    "split": ("axis",), "unbind": ("axis",), "concat": ("axis",),
    "stack": ("axis",), "slice": ("axes",), "strided_slice": ("axes",),
    "tile": ("repeat_times", "x_ndim"), "expand": ("shape", "x_ndim"),
    "broadcast_to": ("shape", "x_ndim"), "cumsum": ("axis",),
    "cumprod": ("axis",), "cummax": ("axis",), "cummin": ("axis",),
    "logcumsumexp": ("axis",), "logsumexp": ("axis",), "p_norm": ("axis",),
    "norm": ("axis",), "pad": ("padded_dims",), "gather": ("axis",),
    "squeeze": ("axis", "x_ndim"), "unsqueeze": ("axis", "x_ndim"),
    "argmax": ("axis",), "argmin": ("axis",),
    "conv2d": ("channel_last",),
    "moe_dispatch": ("x_ndim",), "moe_combine": ("y_ndim",),
}

# Observability (VERDICT r3 weak #4: silent `except: pass` made a broken
# rule indistinguishable from a never-matching one). `FLAGS_spmd_debug=1`
# additionally prints each failure with its traceback.
from ...utils.flags import define_flag, flags as _flags
define_flag("spmd_debug", False,
            "log SPMD rule application failures instead of counting only")

_STATS = {"hits": {}, "errors": {}, "skips": {}, "last_error": {}}


def rule_stats():
    """Per-op counters: {'hits': {op: n}, 'errors': {op: n},
    'skips': {op: n}, 'last_error': {op: repr}}. hits = a rule ran and
    pinned at least one output; skips = rule present but gated off
    (missing attrs / no known input spec / Partial output)."""
    return _STATS


def reset_rule_stats():
    for d in _STATS.values():
        d.clear()


def rules_prometheus_text(*, prefix: str = "paddle_spmd", labels=None,
                          emit_type: bool = True) -> str:
    """rule_stats() through the SHARED exposition renderer (ISSUE 12):
    the hits/errors/skips dicts render one labelled line per op, so a
    broken or never-matching rule is a scrape away; drift test asserts
    the name bijection both ways like every other exposition."""
    from ...profiler.exposition import prometheus_lines
    lines = prometheus_lines(rule_stats(), prefix=prefix, labels=labels,
                             emit_type=emit_type)
    return "\n".join(lines) + "\n" if lines else ""


def _bump(kind, name):
    _STATS[kind][name] = _STATS[kind].get(name, 0) + 1

# rules we deliberately do NOT constrain with on TPU: their reference
# semantics force replication because the reference's kernels are
# single-device, but GSPMD compiles the sharded version with in-graph
# collectives (sharded softmax/norm beat an all-gather)
_SKIP_ON_TPU = {"softmax", "log_softmax", "layer_norm", "rms_norm",
                "reshape", "flatten", "default_data_parallel"}


@contextlib.contextmanager
def spmd_propagation(mesh):
    """Enable per-op rule consultation over `mesh` (a jax Mesh or a
    ProcessMesh). Nestable; inner mesh wins."""
    jmesh = getattr(mesh, "jax_mesh", mesh)
    if not isinstance(jmesh, Mesh):
        raise TypeError(f"spmd_propagation needs a Mesh, got {type(mesh)}")
    if not _STATE.get("registered"):
        # join Profiler.summary() like the comm counters (ISSUE 12) —
        # registered on first activation, so rule-less processes never
        # grow a provider
        from ... import profiler as _profiler
        _profiler.register_counter_provider("spmd_rules", rule_stats)
        _STATE["registered"] = True
    prev = _STATE["mesh"]
    _STATE["mesh"] = jmesh
    try:
        yield jmesh
    finally:
        _STATE["mesh"] = prev


def propagation_mesh() -> Optional[Mesh]:
    return _STATE["mesh"]


def spec_of(t, mesh) -> Optional[P]:
    """The framework-level spec of a Tensor: the spec a previous rule
    recorded, else the NamedSharding of a concrete array on this mesh."""
    s = getattr(t, "_spmd_spec", None)
    if s is not None:
        return s
    d = getattr(t, "_data", None)
    if isinstance(d, jax.Array) and not isinstance(d, jax.core.Tracer):
        sh = d.sharding
        if isinstance(sh, NamedSharding) and sh.mesh.shape == mesh.shape:
            return sh.spec
    return None


def _valid_spec(spec, ndim, mesh) -> bool:
    entries = tuple(spec) if spec is not None else ()
    if len(entries) > ndim:
        return False
    names = set(mesh.shape)
    for e in entries:
        for n in (e if isinstance(e, tuple) else (e,)):
            if n is not None and n not in names:
                return False
    return True


def maybe_constrain(name, in_tensors, out_tensors, kwargs):
    """Consult the rule registry for op `name`; pin output placements.
    Never raises — a rule problem must not break compute (the GSPMD
    fallback is always correct)."""
    mesh = _STATE["mesh"]
    if mesh is None or name not in _RULES or name in _SKIP_ON_TPU:
        return
    needed = _ATTR_DEPENDENT.get(name)
    if needed is not None and not all(k in kwargs for k in needed):
        _bump("skips", name)
        return
    try:
        in_specs = [spec_of(t, mesh) for t in in_tensors]
        if not any(s is not None and any(e is not None for e in tuple(s))
                   for s in in_specs):
            _bump("skips", name)
            return  # nothing known to propagate
        attrs = {k: v for k, v in kwargs.items()
                 if isinstance(v, (int, bool, str, type(None), list, tuple))}
        res = infer_spmd(name, *in_specs, **attrs)
        if res.partial_axes:
            # pending reduction: GSPMD inserts the psum; do not pin
            _bump("skips", name)
            return
        outs = res.out_specs
        if len(outs) == 1 and len(out_tensors) > 1:
            outs = outs * len(out_tensors)
        pinned = False
        for t, spec in zip(out_tensors, outs):
            d = getattr(t, "_data", None)
            if d is None or not hasattr(d, "ndim"):
                continue
            if not _valid_spec(spec, d.ndim, mesh):
                continue
            if not any(e is not None for e in tuple(spec or ())):
                continue
            t._data = jax.lax.with_sharding_constraint(
                d, NamedSharding(mesh, spec))
            t._spmd_spec = spec
            pinned = True
        _bump("hits" if pinned else "skips", name)
    except Exception as e:  # advisory only; GSPMD owns correctness
        _bump("errors", name)
        _STATS["last_error"][name] = repr(e)
        if _flags("spmd_debug"):
            # routed through the shared Diagnostics path (ISSUE 12):
            # the failure lands machine-readable in
            # to_static_report()["purity_diagnostics"] / FALLBACKS.md
            # instead of being lost in stdout; counting stays
            # unconditional as before
            import traceback
            from ...analysis import purity as _purity
            _purity.record_spmd_rule_failure(
                name, e, traceback.format_exc())
