"""ProcessMesh — the device mesh abstraction.

Parity: reference `python/paddle/distributed/auto_parallel/process_mesh.py`
(+ C++ `phi/core/distributed/auto_parallel/process_mesh.h:34`).
TPU-native: wraps `jax.sharding.Mesh` over jax.devices(); axes map onto
ICI dimensions by construction order (outermost axis = slowest/DCN-ish,
innermost = fastest ICI ring), which is jax's device-order behavior.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_global_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names)
        self._process_ids = arr.reshape(-1).tolist()
        devices = jax.devices()
        if arr.size > len(devices):
            # virtual mesh (e.g. authored for a bigger pod): keep ids; the
            # jax Mesh is only materialized when enough devices exist.
            self._jax_mesh = None
        else:
            dev_arr = np.asarray([devices[i] for i in self._process_ids],
                                 dtype=object).reshape(arr.shape)
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    # -- reference API surface --
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return list(self._process_ids)

    @property
    def processes(self):
        return self.process_ids

    @property
    def size(self):
        return int(np.prod(self._shape))

    def get_dim_size(self, dim_name):
        return self._shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        idx = self._process_ids.index(process_id)
        coords = np.unravel_index(idx, self._shape)
        return int(coords[self._dim_names.index(dim_name)])

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh obtained by selecting/moving a dim (reference semantics)."""
        ax = self._dim_names.index(dim_name)
        arr = np.asarray(self._process_ids).reshape(self._shape)
        moved = np.moveaxis(arr, ax, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is None:
            return ProcessMesh(moved, names)
        return ProcessMesh(moved[index], names[1:])

    # -- TPU-native --
    @property
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            raise RuntimeError(
                f"ProcessMesh of size {self.size} exceeds available devices "
                f"({jax.device_count()}); materialize on a larger slice or "
                "use XLA_FLAGS=--xla_force_host_platform_device_count.")
        return self._jax_mesh

    def __enter__(self):
        global _global_mesh
        self._prev = _global_mesh
        _global_mesh = self
        return self

    def __exit__(self, *a):
        global _global_mesh
        _global_mesh = self._prev
        return False

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                self._shape == other._shape and
                self._process_ids == other._process_ids)

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self._process_ids)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names},"
                f" process_ids={self._process_ids[:8]}{'...' if self.size > 8 else ''})")


def get_mesh():
    return _global_mesh


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh
