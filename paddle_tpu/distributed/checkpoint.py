"""Distributed (sharded) checkpoint with load-time resharding.

Parity: reference `python/paddle/distributed/checkpoint/` —
save_state_dict (per-rank metadata gather + dedup, save_state_dict.py:91),
load_state_dict (overlap-based read plan mapping saved shards to target
shards, load_state_dict.py:310-467), async save queue (save_state_dict.py:46),
LocalTensorMetadata (metadata.py:20).

TPU-native: orbax-checkpoint is the battle-tested implementation of exactly
this (per-shard OCDBT/zarr writes + sharding-aware restore that reshards to
the target NamedSharding). We use it as the storage engine and keep the
reference's API shape on top. Async save uses orbax's async checkpointer
(the reference's background-queue analog).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "LocalTensorMetadata",
           "async_save_state_dict"]


class LocalTensorMetadata:
    """Parity: checkpoint/metadata.py:20 — per-shard (offset, shape) record."""

    def __init__(self, global_offset, local_shape, dtype=None):
        self.global_offset = tuple(global_offset)
        self.local_shape = tuple(local_shape)
        self.dtype = dtype

    def __repr__(self):
        return (f"LocalTensorMetadata(offset={self.global_offset}, "
                f"shape={self.local_shape})")


def _unwrap(state_dict):
    flat = {}
    for k, v in state_dict.items():
        flat[k] = v._data if isinstance(v, Tensor) else v
    return flat


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """Save a (possibly sharded) state dict. Each array's shards are written
    once (dedup across replicas is orbax's responsibility, matching the
    reference's rank-0-dedup)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    flat = _unwrap(state_dict)
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(path, "state")
    if os.path.exists(target):
        import shutil
        shutil.rmtree(target)
    ckptr.save(target, flat)
    ckptr.wait_until_finished()
    return path


_async_threads = []


def async_save_state_dict(state_dict, path, **kw):
    """Async save (reference: save_state_dict.py:46 background queue)."""
    t = threading.Thread(target=save_state_dict, args=(dict(state_dict), path),
                         kwargs=kw, daemon=True)
    t.start()
    _async_threads.append(t)
    return t


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Load into `state_dict` IN PLACE, resharding saved arrays onto each
    target tensor's current sharding (the reference's overlap read plan —
    here orbax restores directly into the requested NamedSharding)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    target = os.path.join(path, "state")
    ckptr = ocp.StandardCheckpointer()

    abstract = {}
    for k, v in state_dict.items():
        arr = v._data if isinstance(v, Tensor) else v
        sharding = getattr(arr, "sharding", None)
        abstract[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                           sharding=sharding)
    restored = ckptr.restore(target, abstract)
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            v._data = restored[k]
        else:
            state_dict[k] = restored[k]
    return state_dict
