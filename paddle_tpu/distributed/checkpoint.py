"""Distributed (sharded) checkpoint with load-time resharding.

Parity: reference `python/paddle/distributed/checkpoint/` —
save_state_dict (per-rank metadata gather + dedup, save_state_dict.py:91),
load_state_dict (overlap-based read plan mapping saved shards to target
shards, load_state_dict.py:310-467), async save queue (save_state_dict.py:46),
LocalTensorMetadata (metadata.py:20).

TPU-native: orbax-checkpoint is the battle-tested implementation of exactly
this (per-shard OCDBT/zarr writes + sharding-aware restore that reshards to
the target NamedSharding). We use it as the storage engine and keep the
reference's API shape on top. Async save uses orbax's async checkpointer
(the reference's background-queue analog).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "LocalTensorMetadata",
           "async_save_state_dict", "wait_async_saves", "get_metadata"]


class LocalTensorMetadata:
    """Parity: checkpoint/metadata.py:20 — per-shard (offset, shape) record."""

    def __init__(self, global_offset, local_shape, dtype=None):
        self.global_offset = tuple(global_offset)
        self.local_shape = tuple(local_shape)
        self.dtype = dtype

    def __repr__(self):
        return (f"LocalTensorMetadata(offset={self.global_offset}, "
                f"shape={self.local_shape})")


def _unwrap(state_dict):
    flat = {}
    for k, v in state_dict.items():
        flat[k] = v._data if isinstance(v, Tensor) else v
    return flat


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """Save a (possibly sharded) state dict. Each array's shards are written
    once (dedup across replicas is orbax's responsibility, matching the
    reference's rank-0-dedup)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    flat = _unwrap(state_dict)
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(path, "state")
    if os.path.exists(target):
        import shutil
        shutil.rmtree(target)
    ckptr.save(target, flat)
    ckptr.wait_until_finished()
    return path


_async_ckptr = [None]


def async_save_state_dict(state_dict, path, **kw):
    """Async save (reference: save_state_dict.py:46 background queue).

    Uses orbax's AsyncCheckpointer: `save()` returns only after the
    per-shard device->host snapshot, so the caller may mutate/donate the
    live arrays immediately (the next optimizer step cannot corrupt the
    save), and the file writes proceed in the background — shard-aware on
    multi-host, no full-array gather. `wait_async_saves()` is the
    completion barrier."""
    import orbax.checkpoint as ocp
    if _async_ckptr[0] is None:
        _async_ckptr[0] = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    ckptr = _async_ckptr[0]
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    flat = _unwrap(state_dict)
    target = os.path.join(path, "state")
    if os.path.exists(target):
        import shutil
        ckptr.wait_until_finished()  # never delete under an in-flight write
        shutil.rmtree(target)
    ckptr.save(target, args=ocp.args.StandardSave(flat))
    return ckptr


def wait_async_saves(timeout=None):
    """Block until all pending async saves complete (re-raises writer
    errors). Call before exiting or before reusing a checkpoint dir."""
    if _async_ckptr[0] is not None:
        _async_ckptr[0].wait_until_finished()


def get_metadata(state_dict):
    """Per-tensor shard metadata for the CURRENT process (parity:
    save_state_dict.py:91-145 metadata gather): name -> list of
    LocalTensorMetadata for each addressable shard."""
    meta = {}
    for k, v in _unwrap(state_dict).items():
        if hasattr(v, "addressable_shards"):
            meta[k] = [LocalTensorMetadata(
                tuple(idx.start or 0 for idx in sh.index),
                tuple(sh.data.shape), str(v.dtype))
                for sh in v.addressable_shards]
        else:
            arr = np.asarray(v)
            meta[k] = [LocalTensorMetadata((0,) * arr.ndim, arr.shape,
                                           str(arr.dtype))]
    return meta


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Load into `state_dict` IN PLACE, resharding saved arrays onto each
    target tensor's current sharding (the reference's overlap read plan —
    here orbax restores directly into the requested NamedSharding)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    target = os.path.join(path, "state")
    ckptr = ocp.StandardCheckpointer()

    abstract = {}
    for k, v in state_dict.items():
        arr = v._data if isinstance(v, Tensor) else v
        sharding = getattr(arr, "sharding", None)
        abstract[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                           sharding=sharding)
    restored = ckptr.restore(target, abstract)
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            v._data = restored[k]
        else:
            state_dict[k] = restored[k]
    return state_dict
