"""Sharded checkpoint (placeholder — orbax-backed impl next)."""
__all__ = []
