"""Comm watchdog: hang detection around blocking device/collective waits.

Parity: reference `paddle/phi/core/distributed/comm_task_manager.h` /
`nccl_comm_task.cc` — an async watchdog that flags NCCL collectives that
neither complete nor error within a timeout and broadcasts the failure.

TPU-native: collectives are in-graph, so the hang surface is the blocking
HOST wait (`block_until_ready`, checkpoint barriers, store rendezvous).
`watch()` wraps such a wait with a timer thread that fires a diagnostic
callback (default: dump all Python stacks to stderr) when the deadline
passes — turning a silent multi-host hang into an actionable report.
"""
from __future__ import annotations

import faulthandler
import sys
import threading
import time
from typing import Callable, Optional

__all__ = ["watch", "CommWatchdog", "wait_with_timeout"]


class CommWatchdog:
    """Context manager: run `on_timeout` if the block takes too long.

    >>> with CommWatchdog(timeout=300, desc="allreduce barrier"):
    ...     loss._data.block_until_ready()
    """

    def __init__(self, timeout: float = 600.0, desc: str = "",
                 on_timeout: Optional[Callable] = None, repeat=False):
        self.timeout = timeout
        self.desc = desc
        self.on_timeout = on_timeout or self._default_report
        self.repeat = repeat
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self._closed = False
        self.fired = False

    def _default_report(self):
        sys.stderr.write(
            f"[comm watchdog] {self.desc or 'blocking wait'} exceeded "
            f"{self.timeout:.0f}s — dumping stacks (a peer is likely hung "
            f"or dead; check membership/leases)\n")
        try:
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            pass

    def _fire(self):
        self.fired = True
        try:
            self.on_timeout()
        finally:
            if self.repeat:
                self._arm()

    def _arm(self):
        # never re-arm after __exit__ (a firing callback racing the exit
        # would otherwise leak a recurring timer)
        with self._lock:
            if self._closed:
                return
            self._timer = threading.Timer(self.timeout, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def __enter__(self):
        self._arm()
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
        return False


def watch(timeout=600.0, desc="", on_timeout=None):
    return CommWatchdog(timeout=timeout, desc=desc, on_timeout=on_timeout)


def wait_with_timeout(array, timeout=600.0, desc="device wait"):
    """block_until_ready with a watchdog; raises TimeoutError if the wait
    exceeded the deadline (after firing the diagnostic)."""
    wd = CommWatchdog(timeout=timeout, desc=desc)
    with wd:
        result = array.block_until_ready()
    if wd.fired:
        raise TimeoutError(f"{desc} exceeded {timeout}s")
    return result
