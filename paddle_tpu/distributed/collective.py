"""Groups + functional collectives.

Parity: reference ProcessGroup stack (`paddle/phi/core/distributed/collective/
process_group.h:48`, python `distributed/communication/*`). TPU-native
collapse (SURVEY.md §5): a Group is a view over mesh axes; collectives
inside a pjit/shard_map trace lower to XLA collectives on ICI
(psum/all_gather/ppermute/all_to_all); outside a trace on a single process
they are identity/local ops (world of one rank per process — the reference
semantics for nranks==1).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["Group", "new_group", "get_group", "all_reduce", "all_gather",
           "all_gather_object", "all_to_all", "all_to_all_single", "broadcast",
           "reduce", "scatter", "reduce_scatter", "send", "recv", "barrier",
           "ReduceOp", "is_available", "get_backend", "destroy_process_group",
           "stream", "Task", "comm_stats", "reset_comm_stats",
           "set_comm_stats_enabled", "comm_prometheus_text"]


# ---------------------------------------------------------------------------
# Runtime collective counters (ISSUE 12). One flat dict bump per
# out-of-trace API call: `{prim}_calls`, `{prim}_bytes` (payload from the
# arguments' shape x dtype — NEVER from buffer contents, so tracers count
# too and the numeric path is untouched by construction; the booby-trap
# test pins it), `{prim}_group_size` (largest group seen, a gauge).
# Complements the compile-time IR walk (`profiler.comm`): that accounts
# what a COMPILED program moves, this counts what the eager/host API was
# ASKED to move — including the TCPStore mailbox send/recv path, which
# never appears in any HLO.
# ---------------------------------------------------------------------------
_COMM_STATS: dict = {}
_COMM_ENABLED = [True]
_COMM_REGISTERED = [False]


def _tensor_payload_bytes(*tensors) -> int:
    """Payload bytes from shapes/dtypes only (works on tracers; never
    touches data)."""
    import math
    total = 0
    for t in tensors:
        if t is None:
            continue
        d = getattr(t, "_data", t)
        shape = getattr(d, "shape", None)
        dtype = getattr(d, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += math.prod(shape) * jnp.dtype(dtype).itemsize
    return int(total)


def _bump_comm(prim: str, group, *tensors, nbytes=None):
    if not _COMM_ENABLED[0]:
        return
    if nbytes is None:
        nbytes = _tensor_payload_bytes(*tensors)
    g = group or _default_group()
    s = _COMM_STATS
    s[f"{prim}_calls"] = s.get(f"{prim}_calls", 0) + 1
    s[f"{prim}_bytes"] = s.get(f"{prim}_bytes", 0) + int(nbytes)
    s[f"{prim}_group_size"] = max(s.get(f"{prim}_group_size", 0), g.nranks)
    if not _COMM_REGISTERED[0]:
        # join Profiler.summary() the ServingMetrics way — lazily, so a
        # process that never issues a collective never grows a provider
        from .. import profiler as _profiler
        _profiler.register_counter_provider("distributed_comm", comm_stats)
        _COMM_REGISTERED[0] = True


def comm_stats() -> dict:
    """Flat snapshot of the runtime collective counters (copy). Keys
    exist only for primitives actually called — the exposition registry
    contract (no hand-maintained name lists) surfaces new primitives
    automatically."""
    return dict(_COMM_STATS)


def reset_comm_stats():
    _COMM_STATS.clear()


def set_comm_stats_enabled(enabled: bool) -> bool:
    """Toggle the counters (default on — the cost is one dict bump per
    call). Returns the previous setting. With counting off the recorder
    is never invoked at all (booby-trap test), and on-vs-off training/
    serving results are bit-identical either way: the counters read
    only shapes and dtypes."""
    prev = _COMM_ENABLED[0]
    _COMM_ENABLED[0] = bool(enabled)
    return prev


def comm_prometheus_text(*, prefix: str = "paddle_comm",
                         labels=None, emit_type: bool = True) -> str:
    """comm_stats() through the SHARED exposition renderer
    (`profiler.exposition`): `*_calls` / `*_bytes` typed counter,
    `*_group_size` gauge; the drift test asserts the name bijection
    both ways like the serving/training scrapes."""
    from ..profiler.exposition import prometheus_lines
    snap = comm_stats()
    counter_keys = {k for k in snap
                    if k.endswith("_calls") or k.endswith("_bytes")}
    lines = prometheus_lines(snap, counter_keys=counter_keys,
                             prefix=prefix, labels=labels,
                             emit_type=emit_type)
    return "\n".join(lines) + "\n" if lines else ""


class Task:
    """Async-collective handle (parity: the `task` object returned by every
    reference collective — e.g. communication/stream/all_reduce.py:104 —
    with .wait()/.is_completed()). On TPU the collective is an in-graph op
    scheduled asynchronously by XLA/PJRT, so the `sync_op=False` contract
    is honored truthfully: the returned buffers are async futures already,
    wait() blocks until they are materialized. Inside a trace wait() is a
    no-op (tracers have no buffers; ordering is the compiler's job)."""

    def __init__(self, *tensors):
        self._tensors = [t for t in tensors if t is not None]
        self._waited = False

    def _buffers(self):
        for t in self._tensors:
            d = getattr(t, "_data", t)
            if isinstance(d, jax.core.Tracer):
                continue
            if hasattr(d, "block_until_ready"):
                yield d

    def wait(self, timeout=None):
        for d in self._buffers():
            d.block_until_ready()
        self._waited = True
        return True

    def is_completed(self):
        if self._waited:
            return True
        try:
            return all(d.is_ready() for d in self._buffers())
        except AttributeError:
            return self._waited

    def is_sync(self):
        return self._waited


def _task(sync_op, *tensors) -> Task:
    t = Task(*tensors)
    if sync_op:
        t.wait()
    return t


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A logical communication group = a mesh axis name (in-trace) or a rank
    list (process-level bookkeeping)."""

    def __init__(self, rank: int, ranks: List[int], id: int = 0,
                 axis_name: Optional[str] = None):
        self.rank = rank
        self.ranks = list(ranks)
        self.id = id
        self.axis_name = axis_name

    @property
    def nranks(self):
        return len(self.ranks)

    world_size = nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axis={self.axis_name})"


_groups = {}
_next_gid = [1]


def _default_group():
    if 0 not in _groups:
        from .env import get_rank, get_world_size
        _groups[0] = Group(get_rank(), list(range(max(get_world_size(), 1))), 0)
    return _groups[0]


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    from .env import get_rank, get_world_size
    if ranks is None:
        ranks = list(range(max(get_world_size(), 1)))
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(get_rank() if get_rank() in ranks else -1, ranks, gid, axis_name)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid, _default_group())


def is_available():
    return True


def get_backend(group=None):
    return "xla"


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


def _axis_in_trace(axis_name):
    """True if axis_name is a bound axis in the current shard_map/pmap trace."""
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _resolve_axis(group):
    if group is None:
        group = _default_group()
    return group.axis_name


def _require_trace_or_world1(name, group):
    """Out-of-trace guard: a collective on a >1-rank group whose mesh axis
    is not bound in the current trace would silently return local data —
    wrong answers, not degraded ones. Raise instead (VERDICT r1 weak #10);
    world-of-one groups legitimately no-op."""
    g = group or _default_group()
    if g.nranks > 1:
        # promoted to a reportable diagnostic too (tpu-lint rule A5):
        # FALLBACKS.md / to_static_report() show the rejection alongside
        # the dy2static purity events
        from ..analysis import purity as _purity
        _purity.record_out_of_trace_collective(name, g.nranks, g.axis_name)
        raise RuntimeError(
            f"{name} on a {g.nranks}-rank group (axis="
            f"{g.axis_name!r}) outside a mesh-bound trace would silently "
            "return local data. Run it inside shard_map/to_static with the "
            "axis bound, or use GSPMD sharding constraints for the "
            "compiled path.")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Parity: paddle.distributed.all_reduce (in-place on tensor)."""
    _bump_comm("all_reduce", group, tensor)
    axis = _resolve_axis(group)
    if axis and _axis_in_trace(axis):
        fns = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: lambda x, a: jax.lax.pmean(x, a)}
        out = apply_op("all_reduce", lambda x: fns[op](x, axis), tensor)
        tensor._data = out._data
        tensor._grad_node = out._grad_node
        tensor._grad_out_idx = out._grad_out_idx
        tensor.stop_gradient = out.stop_gradient
        return _task(sync_op, tensor)
    _require_trace_or_world1("all_reduce", group)
    # single-rank group: identity
    return _task(sync_op, tensor)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    _bump_comm("all_gather", group, tensor)
    ax = _resolve_axis(group)
    if ax and _axis_in_trace(ax):
        out = apply_op("all_gather",
                       lambda x: jax.lax.all_gather(x, ax, tiled=False), tensor)
        n = (group or _default_group()).nranks
        from ..ops.manipulation import unbind
        parts = unbind(out, 0)
        tensor_list.clear()
        tensor_list.extend(parts)
        return _task(sync_op, *tensor_list)
    _require_trace_or_world1("all_gather", group)
    tensor_list.clear()
    tensor_list.append(tensor)
    return _task(sync_op, *tensor_list)


def all_gather_object(object_list, obj, group=None):
    _bump_comm("all_gather_object", group, nbytes=0)
    object_list.clear()
    object_list.append(obj)
    return object_list


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    _bump_comm("all_to_all", group, *in_tensor_list)
    ax = _resolve_axis(group)
    if ax and _axis_in_trace(ax):
        from ..ops.manipulation import stack, unbind
        stacked = stack(list(in_tensor_list), axis=0)
        out = apply_op("all_to_all",
                       lambda x: jax.lax.all_to_all(x, ax, split_axis=0,
                                                    concat_axis=0, tiled=False),
                       stacked)
        parts = unbind(out, 0)
        out_tensor_list.clear()
        out_tensor_list.extend(parts)
        return _task(sync_op, *out_tensor_list)
    _require_trace_or_world1("all_to_all", group)
    out_tensor_list.clear()
    out_tensor_list.extend(in_tensor_list)
    return _task(sync_op, *out_tensor_list)


def all_to_all_single(out_tensor, in_tensor, in_split_sizes=None,
                      out_split_sizes=None, group=None, sync_op=True):
    _bump_comm("all_to_all_single", group, in_tensor)
    ax = _resolve_axis(group)
    if ax and _axis_in_trace(ax):
        n = (group or _default_group()).nranks
        out = apply_op(
            "all_to_all_single",
            lambda x: jax.lax.all_to_all(
                x.reshape((n, x.shape[0] // n) + x.shape[1:]), ax,
                split_axis=0, concat_axis=0, tiled=True), in_tensor)
        out_tensor._data = out._data.reshape(out_tensor._data.shape)
        return _task(sync_op, out_tensor)
    _require_trace_or_world1("all_to_all_single", group)
    out_tensor._data = in_tensor._data
    return _task(sync_op, out_tensor)


def broadcast(tensor, src=0, group=None, sync_op=True):
    _bump_comm("broadcast", group, tensor)
    # In-trace SPMD: all ranks compute identically; broadcast is a no-op on
    # replicated values. Cross-process eager: handled by checkpoint/init sync.
    return _task(sync_op, tensor)


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # counted by the all_reduce it delegates to
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    _bump_comm("scatter", group, *(tensor_list or (tensor,)))
    ax = _resolve_axis(group)
    if ax and _axis_in_trace(ax):
        from ..ops.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)
        idx = jax.lax.axis_index(ax)
        out = apply_op("scatter", lambda x: x[idx], stacked)
        tensor._data = out._data
        return _task(sync_op, tensor)
    _require_trace_or_world1("scatter", group)
    if tensor_list:
        tensor._data = tensor_list[src]._data
    return _task(sync_op, tensor)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    _bump_comm("reduce_scatter", group, *tensor_list)
    ax = _resolve_axis(group)
    if ax and _axis_in_trace(ax):
        from ..ops.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)
        out = apply_op("reduce_scatter",
                       lambda x: jax.lax.psum_scatter(x, ax, scatter_dimension=0,
                                                      tiled=False), stacked)
        tensor._data = out._data
        return _task(sync_op, tensor)
    _require_trace_or_world1("reduce_scatter", group)
    if tensor_list:
        acc = tensor_list[0]._data
        for t in tensor_list[1:]:
            acc = acc + t._data
        tensor._data = acc
    return _task(sync_op, tensor)


_P2P_SEQ = {}


def _p2p_store():
    import os
    from . import env as _env
    # paddle_tpu.distributed.launch exports PADDLE_P2P_STORE (the
    # coordinator's sibling port): prefer THAT store for the mailbox —
    # the registry returns the existing instance or lazily creates it
    ep = os.environ.get("PADDLE_P2P_STORE")
    if ep:
        return _env.create_store(ep)
    if _env._store[0] is None:
        raise RuntimeError(
            "cross-process send/recv rides the native TCPStore mailbox: "
            "launch via paddle_tpu.distributed.launch (which exports "
            "PADDLE_P2P_STORE), or call "
            "paddle.distributed.create_store(endpoint) first, on a port "
            "DISTINCT from the jax coordinator (or init_rpc, which "
            "creates one)")
    return _env._store[0]


def send(tensor, dst=0, group=None, sync_op=True):
    """Cross-process point-to-point send (parity: the reference pipeline's
    NCCL p2p, `fleet/meta_parallel/pp_utils/p2p_communication.py:52`).

    TPU-native split: the COMPILED pipeline path keeps stage edges
    in-graph (ppermute, distributed/pipeline.py); this host-side path
    carries eager stage boundaries between PROCESSES over the native
    TCPStore mailbox with per-(src,dst) sequence keys — the transport the
    launcher already provides. Single-process worlds have no second
    process to talk to and raise (in-graph collectives are the tool
    there)."""
    import jax
    _bump_comm("send", group, tensor)
    if jax.process_count() <= 1:
        raise NotImplementedError(
            "send/recv needs a multi-process world (jax.process_count() "
            "> 1); inside one process use distributed.pipeline "
            "(ppermute-based) instead.")
    import pickle
    import numpy as np
    store = _p2p_store()
    rank = jax.process_index()
    seq = _P2P_SEQ.get((rank, dst), 0)
    _P2P_SEQ[(rank, dst)] = seq + 1
    host = np.asarray(jax.device_get(getattr(tensor, "_data", tensor)))
    store.set(f"p2p/{rank}/{dst}/{seq}", pickle.dumps(host))
    return _task(sync_op, tensor)


class _RecvTask(Task):
    """recv with sync_op=False defers the blocking mailbox read to
    wait() — irecv-then-send on both ranks must not deadlock (the
    reference's post-receives-first pattern)."""

    def __init__(self, tensor, fetch):
        super().__init__(tensor)
        self._fetch = fetch
        self._done = False

    def wait(self, timeout=None):
        if not self._done:
            self._fetch()
            self._done = True
        return super().wait(timeout)

    def is_completed(self):
        return self._done


def recv(tensor, src=0, group=None, sync_op=True):
    """Receive matching `send` (fills `tensor._data` like the reference's
    buffer-receiving recv). sync_op=False returns a Task whose wait()
    performs the blocking read; the mailbox key is deleted after a
    successful read so the store does not grow unboundedly."""
    import jax
    _bump_comm("recv", group, tensor)
    if jax.process_count() <= 1:
        raise NotImplementedError(
            "send/recv needs a multi-process world (jax.process_count() "
            "> 1); inside one process use distributed.pipeline "
            "(ppermute-based) instead.")
    import pickle
    store = _p2p_store()
    rank = jax.process_index()
    seq = _P2P_SEQ.get((src, rank), 0)
    _P2P_SEQ[(src, rank)] = seq + 1
    key = f"p2p/{src}/{rank}/{seq}"

    def _fetch():
        raw = store.get(key, wait=True)
        try:
            store.delete_key(key)
        except Exception:
            pass  # cleanup is best-effort; correctness needs only get
        tensor._data = jnp.asarray(pickle.loads(raw))

    if sync_op:
        _fetch()
        return _task(True, tensor)
    return _RecvTask(tensor, _fetch)


def barrier(group=None):
    _bump_comm("barrier", group, nbytes=0)
    jnp.zeros(()).block_until_ready()


def _stream_variant(fn):
    """reference communication/stream/*.py signature: adds
    use_calc_stream (calc-stream vs comm-stream is a CUDA scheduling
    distinction; XLA owns scheduling here, so it only gates the eager
    wait) and returns the Task."""
    def wrapper(*args, sync_op=True, use_calc_stream=False, **kwargs):
        # sync_op defaults True like the reference stream APIs
        # (communication/stream/all_reduce.py:108 declares
        # `sync_op: bool = True`; ADVICE r3 claimed False — checked and
        # the reference says otherwise); use_calc_stream forces the
        # eager wait like the reference's calc-stream semantics
        return fn(*args, sync_op=sync_op or use_calc_stream, **kwargs)
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


class _StreamNamespace:
    """paddle.distributed.stream.* variants — on TPU all collectives are
    in-graph and asynchronously scheduled by XLA; these return the same
    Task handles with the stream-API signature."""
    all_reduce = staticmethod(_stream_variant(all_reduce))
    all_gather = staticmethod(_stream_variant(all_gather))
    all_to_all = staticmethod(_stream_variant(all_to_all))
    broadcast = staticmethod(_stream_variant(broadcast))
    reduce = staticmethod(_stream_variant(reduce))
    scatter = staticmethod(_stream_variant(scatter))
    reduce_scatter = staticmethod(_stream_variant(reduce_scatter))


stream = _StreamNamespace()
