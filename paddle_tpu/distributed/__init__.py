"""paddle_tpu.distributed — the parallelism suite over jax.sharding.

Parity map (reference python/paddle/distributed/, SURVEY.md §2.5):
  - collective API -> .collective (XLA collectives / mesh axes)
  - fleet + hybrid topology -> .fleet (mesh axes [data,pipe,sharding,sep,model])
  - TP/SP layers (mpu) -> .fleet.mpu
  - auto-parallel (ProcessMesh/shard_tensor/reshard) -> .auto_parallel
  - sharding (ZeRO 1/2/3) -> .sharding
  - pipeline parallel -> .pipeline
  - MoE / expert parallel -> .moe
  - sharded checkpoint -> .checkpoint
  - launch CLI -> .launch
"""
from .env import (  # noqa: F401
    barrier_store, create_store, get_rank, get_world_size, init_parallel_env,
    is_initialized, ParallelEnv,
)
from .collective import (  # noqa: F401
    Group, new_group, all_reduce, all_gather, all_gather_object, all_to_all,
    all_to_all_single, broadcast, reduce, scatter, reduce_scatter, send, recv,
    barrier, ReduceOp, is_available, get_backend, destroy_process_group,
    stream, get_group, broadcast_object_list, Task,
)
from .parallel import DataParallel  # noqa: F401

from . import env  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel.api import (  # noqa: F401
    shard_tensor, reshard, dtensor_from_local, dtensor_to_local, shard_layer,
    shard_optimizer, to_static as dist_to_static, unshard_dtensor,
    to_static, DistModel, DistAttr, moe_global_mesh_tensor,
    moe_sub_mesh_tensors,
)
from . import communication  # noqa: F401
from . import extras as _extras  # noqa: F401
from .extras import (  # noqa: F401
    gather, wait, isend, irecv, scatter_object_list, alltoall,
    alltoall_single, gloo_init_parallel_env, gloo_barrier, gloo_release,
    split, spawn, ParallelMode, ReduceType, dtensor_from_fn,
    shard_dataloader, ShardDataloader, shard_scaler, Strategy,
    QueueDataset, InMemoryDataset, CountFilterEntry, ShowClickEntry,
    ProbabilityEntry,
)
from . import io  # noqa: F401
from . import passes  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from .sharding import (  # noqa: F401
    ShardingStage1, ShardingStage2, ShardingStage3,
)
from .auto_parallel.process_mesh import ProcessMesh  # noqa: F401
from .auto_parallel.placement_type import (  # noqa: F401
    Placement, Shard, Replicate, Partial,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401
from . import pipeline  # noqa: F401
from . import moe  # noqa: F401
from . import launch  # noqa: F401
from . import context_parallel  # noqa: F401
from .context_parallel import context_parallel_attention  # noqa: F401
from . import rpc  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import watchdog  # noqa: F401
from . import utils as dist_utils  # noqa: F401
