"""paddle.distributed.communication.stream — stream-variant collectives.

Parity: reference `python/paddle/distributed/communication/stream/*.py`
(each collective with `use_calc_stream`). On TPU, XLA owns scheduling;
the flag only gates the eager wait (see ..collective._stream_variant).
"""
from ..collective import stream as _ns

all_reduce = _ns.all_reduce
all_gather = _ns.all_gather
all_to_all = _ns.all_to_all
broadcast = _ns.broadcast
reduce = _ns.reduce
scatter = _ns.scatter
reduce_scatter = _ns.reduce_scatter

__all__ = ["all_reduce", "all_gather", "all_to_all", "broadcast",
           "reduce", "scatter", "reduce_scatter"]


from ..collective import send, recv  # noqa: E402,F401

alltoall = all_to_all
from ..collective import all_to_all_single as alltoall_single  # noqa: E402
from ..extras import gather  # noqa: E402,F401

__all__ += ["alltoall", "alltoall_single", "send", "recv", "gather"]
