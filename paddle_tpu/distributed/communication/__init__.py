"""paddle.distributed.communication — module-path parity.

Parity: reference `python/paddle/distributed/communication/` (the new
comm library: one module per collective + the stream variants). The
implementations live in ..collective (XLA collectives over mesh axes);
this package provides the importable module structure.
"""
from ..collective import (  # noqa: F401
    all_reduce, all_gather, all_gather_object, all_to_all,
    all_to_all_single, broadcast, broadcast_object_list, reduce, scatter,
    reduce_scatter, send, recv, barrier, ReduceOp, Group, Task,
)
from . import stream  # noqa: F401

__all__ = ["stream", "all_reduce", "all_gather", "all_to_all",
           "broadcast", "reduce", "scatter", "reduce_scatter", "send",
           "recv", "barrier", "ReduceOp", "Group", "Task"]
