"""Expert parallelism (MoE) over the mesh.

Parity: reference MoE stack — `python/paddle/incubate/distributed/models/
moe/moe_layer.py:99,149,263` (MoEScatter/MoEGather alltoall PyLayers +
MoELayer), gate zoo (`moe/gate/`), capacity/routing kernels
(`phi/kernels/number_count_kernel.h`, limit_by_capacity,
prune_gate_by_capacity, random_routing, moe_gate_dispatch/moe_combine),
global_scatter/global_gather collectives.

TPU-native: routing is dense and static-shaped (capacity-bounded one-hot
dispatch einsums — the standard TPU MoE formulation), so XLA keeps
everything on the MXU with no host sync; expert parallelism shards the
expert dim of the dispatched tensor over the 'model'(EP) axis and GSPMD
emits the all_to_all the reference issues via global_scatter/global_gather.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op

__all__ = ["TopKGate", "SwitchGate", "MoELayer", "moe_dispatch_combine",
           "number_count", "limit_by_capacity"]


def number_count(gate_idx, upper_range):
    """Tokens per expert. Parity: phi number_count_kernel."""
    def _f(idx):
        return jnp.bincount(idx.reshape(-1), length=upper_range).astype(jnp.int64)
    return apply_op("number_count", _f, gate_idx)


def limit_by_capacity(expert_count, capacity, n_worker=1):
    """Clamp per-expert token counts. Parity: phi limit_by_capacity."""
    def _f(c):
        cap = jnp.asarray(capacity)
        return jnp.minimum(c, cap).astype(c.dtype)
    return apply_op("limit_by_capacity", _f, expert_count)


def _one_hot_dispatch(gates_arr, topk, capacity):
    """Build dispatch/combine tensors from gate probabilities.

    gates_arr: (tokens, experts) softmax probabilities.
    Returns (dispatch (tokens, experts, capacity) bool-ish float,
             combine (tokens, experts, capacity) float weights,
             aux_loss scalar).
    """
    T, E = gates_arr.shape
    # top-k expert choice per token
    topk_val, topk_idx = jax.lax.top_k(gates_arr, topk)           # (T, k)
    # renormalize chosen gate weights
    topk_val = topk_val / jnp.maximum(
        jnp.sum(topk_val, axis=-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((T, E, capacity), gates_arr.dtype)
    combine = jnp.zeros((T, E, capacity), gates_arr.dtype)
    # position of each token within its expert's capacity buffer
    for j in range(topk):
        e_j = topk_idx[:, j]                                       # (T,)
        onehot = jax.nn.one_hot(e_j, E, dtype=gates_arr.dtype)     # (T, E)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot          # (T, E)
        pos_tok = jnp.sum(pos, axis=1).astype(jnp.int32)           # (T,)
        keep = pos_tok < capacity
        cap_onehot = jax.nn.one_hot(jnp.where(keep, pos_tok, capacity),
                                    capacity + 1,
                                    dtype=gates_arr.dtype)[:, :capacity]
        d_j = onehot[:, :, None] * cap_onehot[:, None, :]          # (T,E,C)
        dispatch = dispatch + d_j
        combine = combine + d_j * topk_val[:, j][:, None, None]

    # load-balancing aux loss (GShard): E * sum_e mean(gates_e)*mean(frac_e)
    me = jnp.mean(gates_arr, axis=0)
    frac = jnp.mean(dispatch.sum(axis=2), axis=0)
    aux = E * jnp.sum(me * frac)
    return dispatch, combine, aux


def moe_dispatch_combine(x, gates, topk, capacity):
    """x: (tokens, d); gates: (tokens, experts). Returns (expert_inputs
    (experts, capacity, d), combine, aux)."""
    def _f(xx, gg):
        dispatch, combine, aux = _one_hot_dispatch(gg, topk, capacity)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, xx)
        return expert_in, combine, aux
    return apply_op("moe_dispatch", _f, x, gates)


class TopKGate(Layer):
    """GShard-style top-k gate. Parity: moe/gate/gshard_gate.py."""

    def __init__(self, d_model, num_experts, topk=2, capacity_factor=1.25):
        super().__init__()
        from ..nn import Linear
        self.wg = Linear(d_model, num_experts, bias_attr=False)
        self.topk = topk
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor

    def forward(self, x):
        logits = self.wg(x)
        return F.softmax(logits, axis=-1)


class SwitchGate(TopKGate):
    """top-1 gate. Parity: moe/gate/switch_gate.py."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        super().__init__(d_model, num_experts, topk=1,
                         capacity_factor=capacity_factor)


class MoELayer(Layer):
    """Mixture-of-experts layer. Parity: moe_layer.py MoELayer.

    experts: LayerList of expert networks (identical structure). With an
    'model'/EP mesh axis live, the (experts, capacity, d) dispatched tensor
    is sharding-constrained on the expert dim, so XLA all_to_alls tokens to
    the expert's owner — the global_scatter/global_gather path.
    """

    def __init__(self, d_model, experts=None, gate=None, num_experts=None,
                 topk=2, capacity_factor=1.25, group=None,
                 recompute_interval=0):
        super().__init__()
        from ..nn import LayerList
        if experts is None:
            raise ValueError("experts list required")
        self.experts = experts if isinstance(experts, LayerList) else \
            LayerList(list(experts))
        self.num_experts = num_experts or len(self.experts)
        self.gate = gate or TopKGate(d_model, self.num_experts, topk,
                                     capacity_factor)
        self.topk = getattr(self.gate, "topk", topk)
        self.capacity_factor = capacity_factor
        self.d_model = d_model
        self.aux_loss = None

    def forward(self, x):
        from ..ops import manipulation as M
        orig_shape = x.shape
        tokens = 1
        for s in orig_shape[:-1]:
            tokens *= s
        xf = M.reshape(x, [tokens, self.d_model])
        gates = self.gate(xf)
        capacity = max(1, int(self.capacity_factor * tokens * self.topk /
                              self.num_experts))
        expert_in, combine, aux = moe_dispatch_combine(xf, gates, self.topk,
                                                       capacity)
        self.aux_loss = aux
        # EP sharding hint: expert dim over the model axis
        from .fleet.mpu import _constraint
        from jax.sharding import PartitionSpec as P
        expert_in = apply_op(
            "ep_shard", lambda a: _constraint(a, P("model", None, None)),
            expert_in)
        # run experts (static python loop -> XLA sees E parallel branches)
        parts = M.split(expert_in, self.num_experts, axis=0)
        outs = [self.experts[e](M.squeeze(parts[e], 0))
                for e in range(self.num_experts)]
        expert_out = M.stack(outs, axis=0)                 # (E, C, d)
        out = apply_op("moe_combine",
                       lambda c, eo: jnp.einsum("tec,ecd->td", c, eo),
                       combine, expert_out)
        return M.reshape(out, orig_shape)
