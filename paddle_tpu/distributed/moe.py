"""Expert parallelism (MoE) over the mesh.

Parity: reference MoE stack — `python/paddle/incubate/distributed/models/
moe/moe_layer.py:99,149,263` (MoEScatter/MoEGather alltoall PyLayers +
MoELayer), gate zoo (`moe/gate/`), capacity/routing kernels
(`phi/kernels/number_count_kernel.h`, limit_by_capacity,
prune_gate_by_capacity, random_routing, moe_gate_dispatch/moe_combine),
global_scatter/global_gather collectives.

TPU-native: routing is static-shaped sort-based dispatch (the
moe_gate_dispatch/moe_combine kernel pair, built from argsort +
scatter/gather instead of CUDA kernels) — O(T·k + E·C) memory, no
(T, E, C) one-hot tensors, no host sync. All experts execute as ONE
batched computation (vmap over stacked expert parameters), so the HLO is
O(1) in the number of experts. Expert parallelism shards the expert dim of
the dispatched (E, C, d) tensor over the 'model'(EP) axis and GSPMD emits
the all_to_all the reference issues via global_scatter/global_gather; the
explicit-collective formulation (`global_scatter`/`global_gather` below)
is available for shard_map code.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_op

__all__ = ["TopKGate", "SwitchGate", "MoELayer", "moe_dispatch_combine",
           "moe_combine", "number_count", "limit_by_capacity",
           "global_scatter", "global_gather"]


def number_count(gate_idx, upper_range):
    """Tokens per expert. Parity: phi number_count_kernel."""
    def _f(idx):
        return jnp.bincount(idx.reshape(-1), length=upper_range).astype(jnp.int64)
    return apply_op("number_count", _f, gate_idx)


def limit_by_capacity(expert_count, capacity, n_worker=1):
    """Clamp per-expert token counts. Parity: phi limit_by_capacity."""
    def _f(c):
        cap = jnp.asarray(capacity)
        return jnp.minimum(c, cap).astype(c.dtype)
    return apply_op("limit_by_capacity", _f, expert_count)


def _sort_dispatch(x, gates, topk, capacity):
    """Sort-based capacity routing (the moe_gate_dispatch kernel).

    x: (T, d); gates: (T, E) softmax probabilities.
    Returns (expert_in (E, C, d), slot_tok (E*C,) int token index per slot,
    slot_w (E*C,) combine weight per slot — 0 for empty slots, aux scalar).

    Tokens are assigned to their top-k experts; assignments are sorted by
    expert id (stable, so earlier tokens win capacity), positions within
    each expert group come from the group offsets, and assignments past
    `capacity` are dropped — all static shapes, no host sync.
    """
    T, d = x.shape
    E = gates.shape[1]
    N = T * topk
    topk_val, topk_idx = jax.lax.top_k(gates, topk)            # (T, k)
    topk_val = topk_val / jnp.maximum(
        jnp.sum(topk_val, axis=-1, keepdims=True), 1e-9)
    flat_e = topk_idx.reshape(-1)                              # (N,)
    flat_w = topk_val.reshape(-1)
    flat_t = jnp.arange(N, dtype=flat_e.dtype) // topk         # token ids
    order = jnp.argsort(flat_e, stable=True)
    se, st, sw = flat_e[order], flat_t[order], flat_w[order]
    counts = jnp.bincount(flat_e, length=E)                    # (E,)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(N, dtype=counts.dtype) - starts[se]
    keep = pos < capacity
    # slot id within the flat (E*C,) buffer; dropped tokens -> sentinel E*C
    slot = jnp.where(keep, se * capacity + pos, E * capacity)
    z = jnp.zeros((E * capacity + 1,), st.dtype)
    slot_tok = z.at[slot].set(st)[:-1]
    slot_w = jnp.zeros((E * capacity + 1,), gates.dtype).at[slot].set(sw)[:-1]
    slot_valid = jnp.zeros((E * capacity + 1,), bool).at[slot].set(True)[:-1]
    expert_in = jnp.where(slot_valid[:, None], x[slot_tok], 0)
    expert_in = expert_in.reshape(E, capacity, d)
    # load-balancing aux loss (GShard): E * sum_e mean(gates_e)*frac_e
    me = jnp.mean(gates, axis=0)
    frac = jnp.minimum(counts, capacity).astype(gates.dtype) / T
    aux = E * jnp.sum(me * frac)
    return expert_in, slot_tok, slot_w * slot_valid, aux


def _sort_combine(expert_out, slot_tok, slot_w, num_tokens):
    """Scatter-add expert outputs back to tokens (the moe_combine kernel)."""
    EC, d = expert_out.shape[0] * expert_out.shape[1], expert_out.shape[2]
    flat = expert_out.reshape(EC, d) * slot_w[:, None]
    return jnp.zeros((num_tokens, d), expert_out.dtype).at[slot_tok].add(flat)


def moe_dispatch_combine(x, gates, topk, capacity):
    """x: (tokens, d); gates: (tokens, experts). Returns (expert_inputs
    (experts, capacity, d), combine_info (slot_tok, slot_w), aux).
    Feed combine_info to `moe_combine` after running the experts."""
    def _f(xx, gg):
        expert_in, slot_tok, slot_w, aux = _sort_dispatch(xx, gg, topk,
                                                          capacity)
        return expert_in, (slot_tok, slot_w), aux
    return apply_op("moe_dispatch", _f, x, gates,
                    op_attrs={"x_ndim": x.ndim})


def moe_combine(expert_out, combine_info, num_tokens):
    """expert_out: (E, C, d); combine_info from moe_dispatch_combine."""
    slot_tok, slot_w = combine_info
    return apply_op(
        "moe_combine",
        lambda eo, stok, sw: _sort_combine(eo, stok, sw, num_tokens),
        expert_out, slot_tok, slot_w,
        op_attrs={"y_ndim": expert_out.ndim})


# ------------------------------------------------- explicit EP collectives
def global_scatter(local_expert_inputs, axis="model"):
    """Inside shard_map: exchange per-expert token slabs so each EP rank
    holds its own experts' tokens from every rank.

    (E, C, d) -> (E/n, n*C, d) over mesh axis `axis` (n = axis size).
    Parity: global_scatter collective
    (`fluid/operators/collective/global_scatter_op.cc`)."""
    return jax.lax.all_to_all(local_expert_inputs, axis,
                              split_axis=0, concat_axis=1, tiled=True)


def global_gather(local_expert_outputs, axis="model"):
    """Inverse of global_scatter: (E/n, n*C, d) -> (E, C, d).
    Parity: global_gather collective."""
    return jax.lax.all_to_all(local_expert_outputs, axis,
                              split_axis=1, concat_axis=0, tiled=True)


class TopKGate(Layer):
    """GShard-style top-k gate. Parity: moe/gate/gshard_gate.py."""

    def __init__(self, d_model, num_experts, topk=2, capacity_factor=1.25):
        super().__init__()
        from ..nn import Linear
        self.wg = Linear(d_model, num_experts, bias_attr=False)
        self.topk = topk
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor

    def forward(self, x):
        logits = self.wg(x)
        return F.softmax(logits, axis=-1)


class SwitchGate(TopKGate):
    """top-1 gate. Parity: moe/gate/switch_gate.py."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        super().__init__(d_model, num_experts, topk=1,
                         capacity_factor=capacity_factor)


class MoELayer(Layer):
    """Mixture-of-experts layer. Parity: moe_layer.py MoELayer.

    experts: LayerList of expert networks (identical structure). All
    experts run as ONE vmapped computation over their stacked parameters —
    compile time and HLO size are O(1) in the expert count. With a
    'model'/EP mesh axis live, the (experts, capacity, d) dispatched tensor
    is sharding-constrained on the expert dim, so XLA all_to_alls tokens to
    the expert's owner — the global_scatter/global_gather path.
    """

    def __init__(self, d_model, experts=None, gate=None, num_experts=None,
                 topk=2, capacity_factor=1.25, group=None,
                 recompute_interval=0):
        super().__init__()
        from ..nn import LayerList
        if experts is None:
            raise ValueError("experts list required")
        self.experts = experts if isinstance(experts, LayerList) else \
            LayerList(list(experts))
        self.num_experts = num_experts or len(self.experts)
        self.gate = gate or TopKGate(d_model, self.num_experts, topk,
                                     capacity_factor)
        self.topk = getattr(self.gate, "topk", topk)
        self.capacity_factor = capacity_factor
        self.d_model = d_model
        self.aux_loss = None

    def forward(self, x):
        from ..jit.api import functional_call
        from ..ops import manipulation as M
        from .fleet.mpu import _constraint
        from jax.sharding import PartitionSpec as P

        orig_shape = x.shape
        tokens = 1
        for s in orig_shape[:-1]:
            tokens *= s
        xf = M.reshape(x, [tokens, self.d_model])
        gates = self.gate(xf)
        capacity = max(1, int(self.capacity_factor * tokens * self.topk /
                              self.num_experts))
        E = self.num_experts
        topk = self.topk
        tmpl = self.experts[0]
        keys = list(tmpl.state_dict().keys())
        # all expert parameters enter the tape op so grads flow per expert
        expert_params = [self.experts[e].state_dict()[k]
                         for e in range(E) for k in keys]

        def _f(xx, gg, *flat):
            expert_in, slot_tok, slot_w, aux = _sort_dispatch(
                xx, gg, topk, capacity)
            # EP sharding hint: expert dim over the model axis (GSPMD emits
            # the global_scatter all_to_all here)
            expert_in = _constraint(expert_in, P("model", None, None))
            stacked = {k: jnp.stack([flat[e * len(keys) + j]
                                     for e in range(E)])
                       for j, k in enumerate(keys)}

            def one(params, xin):
                return functional_call(tmpl, params, Tensor(xin))._data

            expert_out = jax.vmap(one)(stacked, expert_in)    # (E, C, d)
            expert_out = _constraint(expert_out, P("model", None, None))
            out = _sort_combine(expert_out, slot_tok, slot_w, tokens)
            return out, aux

        out, aux = apply_op("moe_layer", _f, xf, gates, *expert_params)
        self.aux_loss = aux
        return M.reshape(out, orig_shape)
