"""Expert parallel MoE (placeholder)."""
__all__ = []
