"""Hybrid-parallel topology: the [data, pipe, sharding, sep, model] axes.

Parity: reference `python/paddle/distributed/fleet/base/topology.py:70-90`
(CommunicateTopology) and `:189` (HybridCommunicateGroup building per-axis
comm groups, incl. fused dp+sep and pp+mp groups at :468-565).

TPU-native: the topology IS a jax.sharding.Mesh with those axis names; a
"comm group" is a mesh-axis view (Group with axis_name), not an NCCL ring.
Axis order maps outer->inner onto the device list, so the innermost axes
(model/sep) ride the fastest ICI dimension — the same locality goal the
reference achieves with its rank-ordering convention.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..collective import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "build_mesh"]

_HYBRID_AXES = ["data", "pipe", "sharding", "sep", "model"]


def build_mesh(dp=1, pp=1, sharding=1, sep=1, mp=1, devices=None) -> Mesh:
    """Build the hybrid mesh. Degree product must equal device count."""
    devices = devices if devices is not None else jax.devices()
    dims = [dp, pp, sharding, sep, mp]
    total = int(np.prod(dims))
    if total != len(devices):
        raise ValueError(f"mesh degrees {dims} (={total}) != devices "
                         f"({len(devices)})")
    arr = np.asarray(devices, dtype=object).reshape(dims)
    return Mesh(arr, tuple(_HYBRID_AXES))


class CommunicateTopology:
    """Parity: CommunicateTopology (topology.py:70)."""

    def __init__(self, hybrid_group_names: Sequence[str] = _HYBRID_AXES,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = [int(d) for d in dims]
        self._world_size = int(np.prod(self._dims))
        self._coords = np.array(
            np.unravel_index(np.arange(self._world_size), self._dims)).T

    def get_hybrid_group_names(self):
        return list(self._parallel_names)

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coord, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in self._coords[rank])

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        ax = self._parallel_names.index(axis_name)
        return [r for r in range(self._world_size)
                if self._coords[r][ax] == index]

    def get_comm_list(self, axis_name):
        """Partition of ranks into groups along `axis_name` (each group
        varies that axis, fixes the others)."""
        ax = self._parallel_names.index(axis_name)
        groups: Dict[tuple, List[int]] = {}
        for r in range(self._world_size):
            key = tuple(c for i, c in enumerate(self._coords[r]) if i != ax)
            groups.setdefault(key, []).append(r)
        return list(groups.values())

    def get_fused_ranks(self, fused_axes):
        """Groups varying all axes in `fused_axes` jointly (reference's
        dp+sep / pp+mp fusion)."""
        axes = [self._parallel_names.index(a) for a in fused_axes]
        groups: Dict[tuple, List[int]] = {}
        for r in range(self._world_size):
            key = tuple(c for i, c in enumerate(self._coords[r])
                        if i not in axes)
            groups.setdefault(key, []).append(r)
        return list(groups.values())


class HybridCommunicateGroup:
    """Parity: HybridCommunicateGroup (topology.py:189). Holds the mesh and
    per-axis Group views + convenience accessors used by fleet wrappers."""

    def __init__(self, topology: CommunicateTopology, rank: int = 0,
                 devices=None):
        self._topo = topology
        self.global_rank = rank
        self.nranks = topology.world_size()
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self._dp_degree = topology.get_dim("data") if "data" in names else 1
        self._pp_degree = topology.get_dim("pipe") if "pipe" in names else 1
        self._sharding_degree = topology.get_dim("sharding") if "sharding" in names else 1
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1
        self._mp_degree = topology.get_dim("model") if "model" in names else 1

        devices = devices if devices is not None else jax.devices()
        if int(np.prod(dims)) == len(devices):
            arr = np.asarray(devices, dtype=object).reshape(dims)
            self.mesh: Optional[Mesh] = Mesh(arr, tuple(names))
        else:
            self.mesh = None  # virtual topology (authored for larger slice)

        self._groups: Dict[str, Group] = {}
        coord = topology.get_coord(rank)
        for ax, name in enumerate(names):
            ranks_lists = topology.get_comm_list(name)
            my = next(g for g in ranks_lists if rank in g)
            self._groups[name] = Group(my.index(rank), my, id=ax + 1,
                                       axis_name=name)

    # ---- reference accessor surface (used by meta_parallel wrappers) ----
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "tensor_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._groups["data"].rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._groups["model"].rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_rank(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._groups["sharding"].rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    # sep
    def get_sep_parallel_rank(self):
        return self._groups["sep"].rank

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def get_dp_sep_parallel_group(self):
        """Fused dp+sep group (grad allreduce domain when sep>1;
        reference topology.py:561)."""
        fused = self._topo.get_fused_ranks(["data", "sep"])
        my = next(g for g in fused if self.global_rank in g)
        return Group(my.index(self.global_rank), my, id=100,
                     axis_name=("data", "sep"))

    def get_check_parallel_group(self, sharding=False):
        return self._groups["sharding" if sharding else "model"]

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = list(self._topo.get_coord(self.global_rank))
        names = self._topo.get_hybrid_group_names()
        coord[names.index("pipe")] = stage_id
        return self._topo.get_rank(**dict(zip(names, coord)))
