"""fleet.base.topology — module-path parity: the implementations live in
paddle_tpu.distributed.fleet.topology (reference
fleet/base/topology.py CommunicateTopology/HybridCommunicateGroup)."""
from ..topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, build_mesh,
)

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "build_mesh"]
