"""fleet.base — module-path parity (reference fleet/base/)."""
from . import topology  # noqa: F401
