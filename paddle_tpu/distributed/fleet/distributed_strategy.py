"""DistributedStrategy — unified parallelism config.

Parity: reference `python/paddle/distributed/fleet/base/distributed_strategy.py`
(protobuf-backed, `framework/distributed_strategy.proto:363` ~275 fields,
see SURVEY.md A.5). TPU rebuild: one plain config object covering the axes
that carry over — hybrid degrees+order, micro-batching, sharding stage,
recompute, amp, fusion toggles.
"""
from __future__ import annotations

import copy

__all__ = ["DistributedStrategy"]


_DEFAULTS = {
    "hybrid_configs": {
        "dp_degree": 1,
        "mp_degree": 1,
        "pp_degree": 1,
        "sharding_degree": 1,
        "sep_degree": 1,
        "order": ["dp", "pp", "sharding", "sep", "mp"],
    },
    "pipeline_configs": {
        "micro_batch_size": 1,
        "accumulate_steps": 1,
        "schedule_mode": "1F1B",   # FThenB | 1F1B | VPP | ZBH1
        "p2p_cache_shape": True,
    },
    "sharding_configs": {
        "stage": 1,
        "degree": 1,
        "offload": False,
        "comm_overlap": True,
    },
    "tensor_parallel_configs": {
        "tensor_parallel_degree": 1,
        "tensor_init_seed": -1,
    },
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "use_dynamic_loss_scaling": True,
        "custom_white_list": [],
        "custom_black_list": [],
        "use_pure_fp16": False,
        "use_bf16": True,
        "level": "O1",
    },
    "recompute_configs": {
        "checkpoints": [],
        "enable_offload": False,
    },
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    # lars/localsgd are CONSUMED by HybridParallelOptimizer (lars swaps a
    # Momentum inner optimizer for LarsMomentum; localsgd syncs params
    # every k_steps); dgc raises NotImplementedError there.
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "lamb_configs": {}, "dgc_configs": {},
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "a_sync_configs": {},
}

_FLAGS = {
    "amp": False, "recompute": False, "pipeline": False, "sharding": False,
    "dgc": False, "lars": False, "lamb": False, "localsgd": False,
    "gradient_merge": False, "a_sync": False, "tensor_parallel": False,
    "heter_ccl_mode": False, "fuse_all_reduce_ops": True,
    "find_unused_parameters": False, "without_graph_optimization": True,
}


class DistributedStrategy:
    def __init__(self):
        self._configs = copy.deepcopy(_DEFAULTS)
        self._flags = dict(_FLAGS)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._configs:
            return self._configs[name]
        if name in self._flags:
            return self._flags[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if name in _DEFAULTS:
            merged = copy.deepcopy(_DEFAULTS[name])
            merged.update(value or {})
            self._configs[name] = merged
        elif name in _FLAGS:
            self._flags[name] = bool(value)
        else:
            raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def to_dict(self):
        return {"configs": copy.deepcopy(self._configs),
                "flags": dict(self._flags)}

    def __repr__(self):
        on = [k for k, v in self._flags.items() if v]
        h = self._configs["hybrid_configs"]
        return (f"DistributedStrategy(dp={h['dp_degree']} mp={h['mp_degree']} "
                f"pp={h['pp_degree']} sharding={h['sharding_degree']} "
                f"sep={h['sep_degree']}, enabled={on})")
