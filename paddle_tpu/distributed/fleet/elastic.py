"""Elastic training: node heartbeat/watch + relaunch protocol.

Parity: reference `python/paddle/distributed/fleet/elastic/manager.py` —
ElasticManager (node registration with TTL lease :254, host watch
callbacks :237,298, scale in/out triggering a rank-map rebuild, the
ELASTIC_EXIT_CODE relaunch protocol) and LauncherInterface (child
launch/watch/stop).

TPU-native: the KV is the native TCPStore (the reference uses etcd) —
each node heartbeats `nodes/<host>` with a timestamp lease; the watcher
thread scans for dead (lease expired) or new hosts and flags a scale
event; the supervisor relaunches the training process with
ELASTIC_EXIT_CODE when membership changed, and the relaunched processes
re-bootstrap through jax.distributed with the new world size.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

__all__ = ["ELASTIC_EXIT_CODE", "ElasticStatus", "ElasticManager",
           "LauncherInterface"]

ELASTIC_EXIT_CODE = 101  # parity: manager.py ELASTIC_EXIT_CODE


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class LauncherInterface:
    """Child-process supervision (parity: manager.py LauncherInterface)."""

    def __init__(self, args: List[str], env=None):
        self.args = list(args)
        self.env = dict(env or os.environ)
        self.proc: Optional[subprocess.Popen] = None

    def launch(self):
        self.proc = subprocess.Popen(self.args, env=self.env)
        return self.proc

    def watch(self):
        """Non-blocking poll: None while running, else the exit code."""
        return self.proc.poll() if self.proc else ELASTIC_EXIT_CODE

    def stop(self, timeout=10):
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()


class ElasticManager:
    """Membership tracking over the TCPStore with TTL-lease heartbeats.

    np spec "min:max" (or int) bounds the elastic world; `exit_code 101`
    from the child requests a restart with the current membership.
    """

    def __init__(self, store=None, host=None, np="1", job_id=None,
                 lease_ttl=6.0, heartbeat_interval=2.0):
        from ..env import create_store
        self.store = store if store is not None else create_store()
        self.host = host or os.environ.get("POD_IP") \
            or f"host-{os.environ.get('PADDLE_TRAINER_ID', '0')}"
        self.job_id = job_id or os.environ.get("PADDLE_ELASTIC_JOB_ID",
                                               "default")
        self.min_np, self.max_np = self._parse_np(np)
        self.lease_ttl = lease_ttl
        self.heartbeat_interval = heartbeat_interval
        self.elastic_level = int(os.environ.get(
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._need_sync = False
        self._known_hosts: List[str] = []

    @staticmethod
    def _parse_np(np_spec):
        if isinstance(np_spec, int):
            return np_spec, np_spec
        if ":" in str(np_spec):
            lo, hi = str(np_spec).split(":")
            return int(lo), int(hi)
        return int(np_spec), int(np_spec)

    # ------------------------------------------------------------ leases
    def _key(self, host):
        return f"elastic/{self.job_id}/nodes/{host}"

    def register(self):
        """Heartbeat this host (parity: manager.py:254 TTL lease)."""
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _beat(self):
        self.store.set(self._key(self.host), repr(time.time()).encode())

    def _hb_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                return

    def deregister(self):
        self._stop.set()
        try:
            self.store.set(self._key(self.host), b"0")
        except Exception:
            pass

    def hosts(self, candidates=None):
        """Live hosts = lease not expired. The store has no native key
        scan; candidate hosts come from env (PADDLE_TRAINER_ENDPOINTS) or
        the caller."""
        cands = candidates
        if cands is None:
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            cands = [e for e in eps.split(",") if e] or [self.host]
        alive = []
        now = time.time()
        for h in cands:
            raw = self.store.get(self._key(h), wait=False)
            if not raw:
                continue
            try:
                ts = float(raw.decode())
            except ValueError:
                continue
            if now - ts <= self.lease_ttl:
                alive.append(h)
        return alive

    # ------------------------------------------------------------- watch
    def watch_once(self, candidates=None):
        """One membership scan -> ElasticStatus (parity: watch callbacks,
        manager.py:237,298)."""
        alive = self.hosts(candidates)
        if self._known_hosts and set(alive) != set(self._known_hosts):
            self._known_hosts = alive
            if len(alive) < self.min_np:
                return ElasticStatus.HOLD     # wait for scale-out
            return ElasticStatus.RESTART      # membership changed: rebuild
        self._known_hosts = alive
        if len(alive) < self.min_np:
            return ElasticStatus.HOLD
        return ElasticStatus.COMPLETED

    # --------------------------------------------------------- supervise
    def run(self, launcher: LauncherInterface, candidates=None,
            poll_interval=0.5, max_restarts=10):
        """Supervise a training child: relaunch on ELASTIC_EXIT_CODE (the
        child requests a restart after membership change), propagate other
        exits. Returns the final exit code."""
        self.register()
        restarts = 0
        try:
            launcher.launch()
            while True:
                rc = launcher.watch()
                if rc is None:
                    time.sleep(poll_interval)
                    continue
                if rc == ELASTIC_EXIT_CODE and restarts < max_restarts:
                    restarts += 1
                    # wait until at least min_np members hold live leases
                    deadline = time.time() + self.lease_ttl * 4
                    while (len(self.hosts(candidates)) < self.min_np
                           and time.time() < deadline):
                        time.sleep(poll_interval)
                    launcher.launch()
                    continue
                return rc
        finally:
            self.deregister()
