"""Megatron-style sequence parallelism utilities.

Parity: reference `python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py` — ScatterOp/GatherOp/AllGatherOp/
ReduceScatterOp PyLayers (:85-127), ColumnSequenceParallelLinear /
RowSequenceParallelLinear (:427,562) overlapping the all-gather /
reduce-scatter with the TP matmuls, and
register_sequence_parallel_allreduce_hooks (:192).

TPU-native: the activations carry a seq-dim sharding over the 'sep' axis
between TP regions; the explicit NCCL all_gather (before the column
matmul) and reduce_scatter (after the row matmul) become GSPMD sharding
constraint transitions — XLA inserts the ICI collectives and overlaps
them with the matmuls via its latency-hiding scheduler, which is the
overlap the reference hand-codes with comm streams. The PyLayer-shaped
functions below are the explicit-op surface for code written against the
reference API.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ...ops.dispatch import apply_op
from .mpu import (ColumnParallelLinear, MODEL_AXIS, RowParallelLinear,
                  _constraint)

__all__ = ["SEP_AXIS", "scatter", "all_gather", "reduce_scatter_sp",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp"]

SEP_AXIS = "sep"


def _seq_spec(ndim):
    """(B, S, ...): sequence dim sharded over sep."""
    return P(*(["data", SEP_AXIS] + [None] * (ndim - 2)))


def _full_spec(ndim):
    return P(*(["data"] + [None] * (ndim - 1)))


def scatter(x):
    """Full sequence -> sequence-sharded (ScatterOp, :85)."""
    return apply_op("sp_scatter",
                    lambda a: _constraint(a, _seq_spec(a.ndim)), x)


def all_gather(x):
    """Sequence-sharded -> full sequence (AllGatherOp, :108)."""
    return apply_op("sp_all_gather",
                    lambda a: _constraint(a, _full_spec(a.ndim)), x)


def reduce_scatter_sp(x):
    """Partial-summed full sequence -> reduced + sequence-sharded
    (ReduceScatterOp, :127). With GSPMD the pending reduction and the
    scatter collapse into one reduce_scatter insertion."""
    return apply_op("sp_reduce_scatter",
                    lambda a: _constraint(a, _seq_spec(a.ndim)), x)


# PyLayer-name aliases (the reference exposes op classes)
class ScatterOp:
    apply = staticmethod(scatter)


class GatherOp:
    apply = staticmethod(all_gather)


class AllGatherOp:
    apply = staticmethod(all_gather)


class ReduceScatterOp:
    apply = staticmethod(reduce_scatter_sp)


def mark_as_sequence_parallel_parameter(param):
    """Parity marker (:168): under SPMD, replicated params need no special
    grad handling — the flag is recorded for checkpoint tooling."""
    param._spec = getattr(param, "_spec", None)
    return param


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Parity (:192): the reference registers backward hooks all-reducing
    sequence-parallel params over the sep group; GSPMD derives exactly
    that reduction from the replicated-parameter/sharded-activation pair,
    so this is a no-op kept for source compatibility."""
    return model


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column TP linear whose INPUT arrives sequence-sharded: the implicit
    all-gather over 'sep' feeds the model-sharded matmul (parity: :427,
    which overlaps the NCCL all_gather with the GEMM)."""

    def forward(self, x):
        x = apply_op(
            "csp_in", lambda a: _constraint(a, _seq_spec(a.ndim)), x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row TP linear whose OUTPUT returns sequence-sharded: the TP partial
    sum and the sequence scatter fuse into one reduce_scatter over
    ('sep' x 'model') (parity: :562)."""

    def forward(self, x):
        if not self.input_is_parallel:
            x = apply_op(
                "rsp_in",
                lambda a: _constraint(
                    a, P(*([None] * (a.ndim - 1) + [MODEL_AXIS]))), x)
        from ...nn import functional as F
        out = F.linear(x, self.weight, None)
        out = apply_op(
            "rsp_out", lambda a: _constraint(a, _seq_spec(a.ndim)), out)
        if self.bias is not None:
            out = out + self.bias
        return out
