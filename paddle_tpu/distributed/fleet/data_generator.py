"""fleet data generators (parity: reference
fleet/data_generator/data_generator.py — the text-protocol generators
feeding slot-based data pipelines). Pure python in the reference too;
implemented fully: user subclasses override generate_sample and the
generator renders the multi-slot line protocol."""
from __future__ import annotations

import sys

__all__ = ["MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._batch = 1
        self._proto_info = None

    def set_batch(self, batch_size):
        self._batch = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, userdef):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            it = self.generate_sample(line)
            if it is None:
                continue
            for user in it():
                sys.stdout.write(self._gen_str(user))

    def run_from_memory(self):
        out = []
        it = self.generate_sample(None)
        for user in it():
            out.append(self._gen_str(user))
        return out


class MultiSlotStringDataGenerator(DataGenerator):
    """Line protocol: `<n> <v1> ... <vn>` per (name, values) slot, values
    kept as strings."""

    def _gen_str(self, userdef):
        parts = []
        for _, values in userdef:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Same protocol with type checking: all values of a slot must be
    int or float (the reference validates identically)."""

    def _gen_str(self, userdef):
        parts = []
        for name, values in userdef:
            if not values:
                raise ValueError(f"slot {name}: empty value list")
            if not all(isinstance(v, (int, float)) for v in values):
                raise ValueError(
                    f"slot {name}: values must be int/float, got "
                    f"{[type(v).__name__ for v in values]}")
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
