"""HybridParallelOptimizer + TP-aware grad clip.

Parity: reference `fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:266` and `HybridParallelClipGrad:42` (global
norm computed across model-parallel shards).

TPU-native: when parameters are GSPMD-sharded jax.Arrays, jnp.sum over a
sharded array already reduces across the mesh — the cross-rank psum the
reference's clip has to issue explicitly is implicit here.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)
        # gradient merge (parity: fleet meta-optimizer gradient_merge /
        # GradientMergeOptimizer): accumulate k_steps of grads, apply the
        # (averaged) update every k-th step
        gm = bool(strategy is not None
                  and getattr(strategy, "gradient_merge", False))
        cfg = (getattr(strategy, "gradient_merge_configs", {})
               if gm else {})
        self._gm_k = int(cfg.get("k_steps", 1)) if gm else 1
        self._gm_avg = bool(cfg.get("avg", True))
        self._gm_step = 0
        self._gm_acc = None

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        if self._gm_k <= 1:
            self._inner_opt.step()
            return
        params = self._inner_opt._parameter_list
        if self._gm_acc is None:
            self._gm_acc = [None] * len(params)
        for i, p in enumerate(params):
            if p.grad is not None:
                g = p.grad._data.astype(jnp.float32)
                self._gm_acc[i] = g if self._gm_acc[i] is None \
                    else self._gm_acc[i] + g
        self._gm_step += 1
        if self._gm_step % self._gm_k != 0:
            self._inner_opt.clear_grad()     # grads are banked; skip apply
            return
        scale = 1.0 / self._gm_k if self._gm_avg else 1.0
        for p, acc in zip(params, self._gm_acc):
            if acc is not None:
                p.grad = Tensor((acc * scale).astype(p._data.dtype))
        self._gm_acc = None
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        """ADVICE r2: only route through the wrapper's step() when
        gradient-merge banking is active; otherwise delegate to the inner
        optimizer's minimize. Never clears gradients (reference
        hybrid_parallel_optimizer.py:266 contract — callers inspect
        p.grad after minimize) and returns (optimize_ops, params_grads).
        Note: with banking active, the k-1 banked steps DO clear the
        per-step grads inside step() — that is the banking contract, the
        accumulated gradient lives in the wrapper."""
        if self._gm_k <= 1:
            return self._inner_opt.minimize(loss, *a, **k)
        loss.backward()
        params = self._inner_opt._parameter_list
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        self.step()
        return [], params_grads

    def state_dict(self):
        sd = self._inner_opt.state_dict()
        if self._gm_k > 1:
            sd = dict(sd)
            sd["_gm_step"] = self._gm_step
            # copy: the live accumulator list mutates as training goes on
            sd["_gm_acc"] = None if self._gm_acc is None \
                else list(self._gm_acc)
        return sd

    def set_state_dict(self, sd):
        if "_gm_step" in sd or "_gm_acc" in sd:
            # strip gm keys unconditionally — a gm-disabled loader must
            # not leak them into the inner optimizer's key parser
            sd = dict(sd)
            step = sd.pop("_gm_step", 0)
            acc = sd.pop("_gm_acc", None)
            if self._gm_k > 1:
                self._gm_step = int(step)
                self._gm_acc = None if acc is None else list(acc)
        return self._inner_opt.set_state_dict(sd)
