"""HybridParallelOptimizer + TP-aware grad clip.

Parity: reference `fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:266` and `HybridParallelClipGrad:42` (global
norm computed across model-parallel shards).

TPU-native: when parameters are GSPMD-sharded jax.Arrays, jnp.sum over a
sharded array already reduces across the mesh — the cross-rank psum the
reference's clip has to issue explicitly is implicit here.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # meta-optimizer strategy flags (VERDICT r2 missing #5: a flag the
        # runtime silently ignores is worse than an absent feature)
        if strategy is not None and getattr(strategy, "dgc", False):
            raise NotImplementedError(
                "strategy.dgc: deep gradient compression is a GPU/NCCL-era "
                "bandwidth optimization this TPU build does not implement "
                "(reference fleet/meta_optimizers/dgc_optimizer.py); unset "
                "the flag — on TPU the in-graph reduce_scatter/all_gather "
                "path over ICI covers the same regime")
        if strategy is not None and getattr(strategy, "lars", False):
            self._inner_opt = optimizer = self._to_lars(optimizer, strategy)
        if optimizer._grad_clip is not None and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)
        # gradient merge (parity: fleet meta-optimizer gradient_merge /
        # GradientMergeOptimizer): accumulate k_steps of grads, apply the
        # (averaged) update every k-th step
        gm = bool(strategy is not None
                  and getattr(strategy, "gradient_merge", False))
        cfg = (getattr(strategy, "gradient_merge_configs", {})
               if gm else {})
        self._gm_k = int(cfg.get("k_steps", 1)) if gm else 1
        self._gm_avg = bool(cfg.get("avg", True))
        self._gm_step = 0
        self._gm_acc = None
        # localsgd (parity: meta_optimizers/localsgd_optimizer.py): run
        # k_steps local updates, then average parameters over the data
        # axis. The averaging is in-trace (lax.pmean) when the data axis
        # is live; on a 1-rank group it is the identity.
        ls = bool(strategy is not None
                  and getattr(strategy, "localsgd", False))
        lcfg = (getattr(strategy, "localsgd_configs", {}) if ls else {})
        self._ls_k = int(lcfg.get("k_steps", 1)) if ls else 0
        self._ls_begin = int(lcfg.get("begin_step", 1)) if ls else 0
        self._ls_step = 0
        self._ls_synced = 0  # observability: how many param syncs ran

    @staticmethod
    def _to_lars(optimizer, strategy):
        """strategy.lars=True: swap a Momentum inner optimizer for
        LarsMomentum (reference lars_optimizer.py:45-58 does the same
        substitution; a non-Momentum inner optimizer is a hard error here
        rather than the reference's silent warn-and-ignore)."""
        from ...optimizer.optimizer import Momentum
        from ...incubate.optimizer import LarsMomentum
        if isinstance(optimizer, LarsMomentum):
            return optimizer
        if not isinstance(optimizer, Momentum):
            raise TypeError(
                "strategy.lars requires a Momentum inner optimizer, got "
                f"{type(optimizer).__name__} (reference lars_optimizer "
                "applies only to Momentum)")
        cfg = getattr(strategy, "lars_configs", {}) or {}
        return LarsMomentum(
            learning_rate=optimizer._learning_rate,
            momentum=optimizer._momentum,
            parameters=optimizer._parameter_list,
            lars_coeff=float(cfg.get("lars_coeff", 0.001)),
            lars_weight_decay=float(cfg.get("lars_weight_decay", 0.0005)),
            epsilon=float(cfg.get("epsilon", 0.0)),
            exclude_from_weight_decay=cfg.get("exclude_from_weight_decay"),
            grad_clip=optimizer._grad_clip,
            multi_precision=optimizer._multi_precision)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def _localsgd_sync(self):
        """Average parameters over the data axis (the k-th local step's
        model sync; reference localsgd_optimizer.py:141 `communicate`).
        In-trace: lax.pmean over 'data'. Eager on a 1-rank data group:
        identity. Eager on a multi-rank group: error by design, matching
        the repo's out-of-trace collective contract."""
        import jax
        from ..collective import _axis_in_trace
        dp = (self._hcg.get_data_parallel_world_size()
              if self._hcg is not None else 1)
        if _axis_in_trace("data"):
            for p in self._inner_opt._parameter_list:
                p._data = jax.lax.pmean(p._data, "data")
        elif dp > 1:
            raise RuntimeError(
                "localsgd parameter sync over a >1-rank data group must "
                "run inside the compiled step (shard_map over the 'data' "
                "axis); out-of-trace collectives are rejected on purpose")
        self._ls_synced += 1

    def _after_apply(self):
        """Post-update hooks shared by both step paths (localsgd sync)."""
        if self._ls_k <= 0:
            return
        self._ls_step += 1
        if (self._ls_step >= self._ls_begin
                and self._ls_step % self._ls_k == 0):
            self._localsgd_sync()

    def step(self):
        if self._gm_k <= 1:
            self._inner_opt.step()
            self._after_apply()
            return
        params = self._inner_opt._parameter_list
        if self._gm_acc is None:
            self._gm_acc = [None] * len(params)
        for i, p in enumerate(params):
            if p.grad is not None:
                g = p.grad._data.astype(jnp.float32)
                self._gm_acc[i] = g if self._gm_acc[i] is None \
                    else self._gm_acc[i] + g
        self._gm_step += 1
        if self._gm_step % self._gm_k != 0:
            self._inner_opt.clear_grad()     # grads are banked; skip apply
            return
        scale = 1.0 / self._gm_k if self._gm_avg else 1.0
        for p, acc in zip(params, self._gm_acc):
            if acc is not None:
                p.grad = Tensor((acc * scale).astype(p._data.dtype))
        self._gm_acc = None
        self._inner_opt.step()
        self._after_apply()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        """ADVICE r2: only route through the wrapper's step() when
        gradient-merge banking is active; otherwise delegate to the inner
        optimizer's minimize. Never clears gradients (reference
        hybrid_parallel_optimizer.py:266 contract — callers inspect
        p.grad after minimize) and returns (optimize_ops, params_grads).
        Note: with banking active, the k-1 banked steps DO clear the
        per-step grads inside step() — that is the banking contract, the
        accumulated gradient lives in the wrapper."""
        if self._gm_k <= 1:
            return self._inner_opt.minimize(loss, *a, **k)
        loss.backward()
        params = self._inner_opt._parameter_list
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        self.step()
        return [], params_grads

    def state_dict(self):
        sd = self._inner_opt.state_dict()
        if self._gm_k > 1:
            sd = dict(sd)
            sd["_gm_step"] = self._gm_step
            # copy: the live accumulator list mutates as training goes on
            sd["_gm_acc"] = None if self._gm_acc is None \
                else list(self._gm_acc)
        return sd

    def set_state_dict(self, sd):
        if "_gm_step" in sd or "_gm_acc" in sd:
            # strip gm keys unconditionally — a gm-disabled loader must
            # not leak them into the inner optimizer's key parser
            sd = dict(sd)
            step = sd.pop("_gm_step", 0)
            acc = sd.pop("_gm_acc", None)
            if self._gm_k > 1:
                self._gm_step = int(step)
                self._gm_acc = None if acc is None else list(acc)
        return self._inner_opt.set_state_dict(sd)
