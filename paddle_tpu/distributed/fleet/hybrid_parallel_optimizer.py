"""HybridParallelOptimizer + TP-aware grad clip.

Parity: reference `fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:266` and `HybridParallelClipGrad:42` (global
norm computed across model-parallel shards).

TPU-native: when parameters are GSPMD-sharded jax.Arrays, jnp.sum over a
sharded array already reduces across the mesh — the cross-rank psum the
reference's clip has to issue explicitly is implicit here.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        return self._inner_opt.minimize(loss, *a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
