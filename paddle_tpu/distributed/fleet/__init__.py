"""paddle_tpu.distributed.fleet — hybrid-parallel facade.

Parity: reference python/paddle/distributed/fleet/.
"""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup, build_mesh  # noqa: F401
from .fleet import (  # noqa: F401
    init, is_initialized, distributed_model, distributed_optimizer,
    get_hybrid_communicate_group, collective_perf, UtilBase, Fleet, util,
)
from .role_maker import (  # noqa: F401
    Role, UserDefinedRoleMaker, PaddleCloudRoleMaker,
)
from .data_generator import (  # noqa: F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from . import base  # noqa: F401
from . import utils  # noqa: F401
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .meta_parallel import (  # noqa: F401
    TensorParallel, ShardingParallel, SegmentParallel, PipelineParallel,
)
from .hybrid_parallel_optimizer import HybridParallelOptimizer  # noqa: F401
from . import mpu  # noqa: F401
from . import elastic  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .mpu import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, get_rng_state_tracker,
)


def __getattr__(name):
    # live view of the hybrid group (fleet.init mutates fleet.fleet._hcg)
    if name == "_hcg":
        from . import fleet as _f
        return _f._hcg
    raise AttributeError(name)
