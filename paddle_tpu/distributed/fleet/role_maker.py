"""fleet role makers (parity: reference fleet/base/role_maker.py).

On the TPU build every process is a collective worker; the role makers
are env-derived config objects (the PS server/heter roles are excluded
per SURVEY A.7 — asking for a server role raises)."""
from __future__ import annotations

import os

__all__ = ["Role", "UserDefinedRoleMaker", "PaddleCloudRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._role = kwargs.get("role", Role.WORKER)
        if self._role == Role.SERVER:
            raise NotImplementedError(
                "parameter-server roles are not part of the TPU build "
                "(SURVEY A.7); every process is a collective WORKER")

    def _is_worker(self):
        return True

    is_worker = _is_worker

    def _is_server(self):
        return False

    is_server = _is_server

    def _worker_num(self):
        from ..env import get_world_size
        return max(get_world_size(), 1)

    worker_num = _worker_num

    def _worker_index(self):
        from ..env import get_rank
        return get_rank()

    worker_index = _worker_index

    def _role_id(self):
        return self._worker_index()


class UserDefinedRoleMaker(RoleMakerBase):
    """Parity: explicit ranks/endpoints config."""

    def __init__(self, is_collective=True, current_id=0, role=Role.WORKER,
                 worker_num=1, worker_endpoints=None, **kwargs):
        super().__init__(is_collective, role=role, **kwargs)
        self._current_id = int(current_id)
        self._n = int(worker_num)
        self._endpoints = list(worker_endpoints or [])

    def _worker_num(self):
        return self._n

    worker_num = _worker_num

    def _worker_index(self):
        return self._current_id

    worker_index = _worker_index


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parity: env-driven role maker (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__(is_collective, **kwargs)
        self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._endpoints = [e for e in os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]

    def _worker_num(self):
        return self._n

    worker_num = _worker_num

    def _worker_index(self):
        return self._current_id

    worker_index = _worker_index
