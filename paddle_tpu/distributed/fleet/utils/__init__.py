"""fleet.utils (parity: reference fleet/utils/__init__.py __all__ =
[LocalFS, recompute, DistributedInfer, HDFSClient])."""
from __future__ import annotations

import os
import shutil

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient"]


def recompute(function, *args, **kwargs):
    """Parity: fleet.utils.recompute (reference fleet/recompute/
    recompute.py — drop activations in forward, recompute in backward).
    TPU-native: jax.checkpoint over the Tensor-level function; the tape
    records ONE op whose vjp re-runs the rematerialized forward."""
    import jax
    from ....core.tensor import Tensor
    from ....ops.dispatch import apply_op

    kwargs.pop("use_reentrant", None)   # accepted, meaningless here
    kwargs.pop("preserve_rng_state", None)
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    def _f(*arrays):
        full = list(args)
        for i, a in zip(tensor_idx, arrays):
            full[i] = Tensor(a)
        out = function(*full, **kwargs)
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    return apply_op("recompute", jax.checkpoint(_f),
                    *[args[i] for i in tensor_idx])


class LocalFS:
    """Parity: fleet/utils/fs.py LocalFS — local-filesystem client."""

    def ls_dir(self, path):
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    mv = rename

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient:
    """HDFS client surface; no hadoop runtime in the TPU image."""

    def __init__(self, hadoop_home=None, configs=None, *a, **k):
        raise NotImplementedError(
            "HDFS is not available in the TPU build; use LocalFS or mount "
            "the data through the host filesystem")


class DistributedInfer:
    """PS-era distributed inference helper; excluded per SURVEY A.7."""

    def __init__(self, main_program=None, startup_program=None):
        raise NotImplementedError(
            "DistributedInfer targets the parameter-server runtime "
            "(SURVEY A.7); use paddle_tpu.inference.Predictor")
