"""fleet.utils (parity: reference fleet/utils/__init__.py __all__ =
[LocalFS, recompute, DistributedInfer, HDFSClient])."""
from __future__ import annotations

import os
import shutil

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient",
           "recompute_sequential", "recompute_hybrid"]


def recompute(function, *args, **kwargs):
    """Parity: fleet.utils.recompute (reference fleet/recompute/
    recompute.py — drop activations in forward, recompute in backward).
    TPU-native: jax.checkpoint over the Tensor-level function; the tape
    records ONE op whose vjp re-runs the rematerialized forward.

    TPU-native extensions: `offload=True` applies the
    offload-dots-to-host remat policy (saved matmul residuals live in
    pinned host memory instead of HBM — the role of the reference
    recompute_hybrid's CPU offload); `policy=` passes any
    jax.checkpoint_policies entry through for finer control."""
    import jax
    from ....core.tensor import Tensor
    from ....ops.dispatch import apply_op

    kwargs.pop("use_reentrant", None)   # accepted, meaningless here
    kwargs.pop("preserve_rng_state", None)
    policy = kwargs.pop("policy", None)
    if kwargs.pop("offload", False) and policy is None:
        policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    # Layer parameters enter as differentiable INPUTS of the checkpointed
    # region (swapped in for the trace) — otherwise they would be baked
    # into the closure as constants and get NO gradients (the reference's
    # recompute backpropagates into the block's weights).
    params = [p for p in function.parameters()
              if not p.stop_gradient] \
        if hasattr(function, "parameters") else []
    n_args = len(tensor_idx)

    def _f(*arrays):
        full = list(args)
        for i, a in zip(tensor_idx, arrays[:n_args]):
            full[i] = Tensor(a)
        saved = [p._data for p in params]
        try:
            for p, a in zip(params, arrays[n_args:]):
                p._data = a
            out = function(*full, **kwargs)
        finally:
            for p, s in zip(params, saved):
                p._data = s
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    return apply_op("recompute", jax.checkpoint(_f, policy=policy),
                    *([args[i] for i in tensor_idx] + params))


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Parity: reference fleet/recompute/recompute.py:622
    recompute_sequential — chunk a Sequential into ctx['segments']
    segments; every segment except the last is recomputed in backward
    (the reference runs the final segment plain, same here).

    ctx keys: 'segments' (int, default 1), 'preserve_rng_state'
    (accepted; RNG determinism is structural here — jax.checkpoint
    replays the same traced program, so the forward RNG is preserved by
    construction)."""
    segments = int(ctx.get("segments", 1))
    if hasattr(functions, "_sub_layers"):     # nn.Sequential
        funcs = list(functions._sub_layers.values())
    else:
        funcs = list(functions)

    class _Segment:
        """Callable over funcs[begin..end] exposing their parameters so
        `recompute` threads them as differentiable inputs."""

        def __init__(self, begin, end):
            self.begin, self.end = begin, end

        def parameters(self):
            ps = []
            for f in funcs[self.begin:self.end + 1]:
                if hasattr(f, "parameters"):
                    ps.extend(f.parameters())
            return ps

        def __call__(self, *inputs, **kw):
            # the FIRST layer of a segment may take the user's full
            # (*args, **kwargs); later layers chain single values
            # (Sequential contract, reference _run_func)
            x = funcs[self.begin](*inputs, **kw)
            for i in range(self.begin + 1, self.end + 1):
                x = funcs[i](x)
            return x

    def _run(begin, end):
        return _Segment(begin, end)

    segments = min(segments, len(funcs))   # never index past the layers
    if segments <= 1 or len(funcs) < 2:
        return recompute(_run(0, len(funcs) - 1), *args, **kwargs)
    segment_size = max(len(funcs) // segments, 1)
    end = segment_size - 1
    out = recompute(_run(0, end), *args, **kwargs)
    for begin in range(segment_size, segment_size * (segments - 1),
                       segment_size):
        end = begin + segment_size - 1
        out = recompute(_run(begin, end), out)
    return _run(end + 1, len(funcs) - 1)(out)


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Parity: reference fleet/recompute/recompute_hybrid.py:265
    recompute_hybrid — recompute in the hybrid-parallel scene.

    ctx keys: 'mp_group' (required, like the reference), 'offload' and
    'partition'. TPU-native mapping: 'offload' applies the
    offload-dots-to-host remat policy (saved residuals in pinned host
    memory — the reference's CPU offload of cached activations);
    'partition' stays subsumed: what little the policy saves rides
    GSPMD's sharding of the traced residuals over the mp group."""
    if ctx.get("mp_group", None) is None:
        raise AssertionError(
            "ctx must contains mp_group and mp_group can not be None.")
    ctx.get("partition", False)
    kwargs["offload"] = bool(ctx.get("offload", False))
    return recompute(function, *args, **kwargs)


class LocalFS:
    """Parity: fleet/utils/fs.py LocalFS — local-filesystem client."""

    def ls_dir(self, path):
        dirs, files = [], []
        for e in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    mv = rename

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient:
    """HDFS client surface; no hadoop runtime in the TPU image."""

    def __init__(self, hadoop_home=None, configs=None, *a, **k):
        raise NotImplementedError(
            "HDFS is not available in the TPU build; use LocalFS or mount "
            "the data through the host filesystem")


class DistributedInfer:
    """PS-era distributed inference helper; excluded per SURVEY A.7."""

    def __init__(self, main_program=None, startup_program=None):
        raise NotImplementedError(
            "DistributedInfer targets the parameter-server runtime "
            "(SURVEY A.7); use paddle_tpu.inference.Predictor")
