"""Hybrid-parallel model wrappers.

Parity: reference `fleet/meta_parallel/` — `TensorParallel` (tensor_parallel.
py:28), `ShardingParallel`, `SegmentParallel` (segment_parallel.py:26),
`PipelineParallel` with FThenB / 1F1B micro-batch schedules
(pipeline_parallel.py:245,565,2018).

TPU-native notes: parameter broadcast/sync at wrap time is a no-op in
single-process SPMD (one copy of truth); gradient synchronization happens
either via GSPMD (sharded batch axis) or explicitly in-trace. The PP
wrapper here provides the reference's micro-batch semantics (gradient
accumulation with schedule-ordered fwd/bwd); the throughput-oriented
in-graph pipeline (shard_map + ppermute over the 'pipe' axis) lives in
distributed.pipeline and is used by the model recipes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from .pp_layers import PipelineLayer

__all__ = ["TensorParallel", "ShardingParallel", "SegmentParallel",
           "PipelineParallel"]


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class TensorParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/tensor_parallel.py:28."""


class ShardingParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/sharding_parallel.py."""


class SegmentParallel(_MetaParallelBase):
    """Sequence/segment parallel wrapper (parity: segment_parallel.py:26).
    Activations are sharded along the sequence dim over the 'sep' axis;
    attention uses all-to-all (Ulysses) via the sp utilities."""


class PipelineParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/pipeline_parallel.py (train_batch:810,
    forward_backward_pipeline 1F1B:565)."""

    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel needs a PipelineLayer")
        super().__init__(layers, hcg, strategy)
        pcfg = strategy.pipeline_configs if strategy else {}
        self._micro_batch_size = pcfg.get("micro_batch_size", 1)
        self._accumulate_steps = pcfg.get("accumulate_steps", 1)
        self._schedule = pcfg.get("schedule_mode", "1F1B")
        self._step_callbacks = []

    def register_micro_step_callback(self, fn):
        """Parity: pipeline_parallel.py:166 micro-batch step callbacks."""
        self._step_callbacks.append(fn)

    def _split_micro(self, data):
        from ...ops.manipulation import split
        x, y = data
        n = self._accumulate_steps
        xs = split(x, n, axis=0) if n > 1 else [x]
        ys = split(y, n, axis=0) if n > 1 else [y]
        return list(zip(xs, ys))

    def forward_backward_pipeline(self, data, scaler=None):
        """Micro-batch schedule. On TPU every 'rank' sees the whole graph
        (SPMD); the 1F1B ordering is realized for memory by interleaving
        fwd/bwd over micro-batches — backward for micro i is issued as soon
        as its forward completes in the steady state. schedule_mode in
        {ZB-H1, ZB, zero_bubble, ZBH1} routes through the fleet executor's
        ZeroBubbleRunner with the backward split per stage segment."""
        micros = self._split_micro(data)
        from ..pipeline import ZB_SCHEDULES, ZBV_SCHEDULES
        if self._schedule in ZB_SCHEDULES or self._schedule == "ZBH1" \
                or self._schedule in ZBV_SCHEDULES:
            return self._zb_forward_backward(micros, scaler)
        total = None
        n = len(micros)
        warmup = min(self._hcg.get_pipe_parallel_world_size() - 1, n) \
            if self._schedule == "1F1B" else n
        pending = []

        def fwd(mb):
            x, y = mb
            out = self._layers.forward(x)
            loss = self._layers.loss(out, y)
            if scaler is not None:
                loss_s = scaler.scale(loss)
            else:
                loss_s = loss
            return loss, loss_s

        def bwd(loss_s):
            (loss_s / n).backward()

        # warmup forwards
        for i in range(warmup):
            pending.append(fwd(micros[i]))
        # steady 1F1B
        for i in range(warmup, n):
            loss, loss_s = pending.pop(0)
            total = loss.detach() if total is None else total + loss.detach()
            bwd(loss_s)
            pending.append(fwd(micros[i]))
            for cb in self._step_callbacks:
                cb(i)
        # cooldown
        for loss, loss_s in pending:
            total = loss.detach() if total is None else total + loss.detach()
            bwd(loss_s)
        return total / n if total is not None else None

    def _zb_forward_backward(self, micros, scaler=None):
        """EXECUTED ZB-H1 over the PipelineLayer's stage segments: the
        fleet executor Plan runs split-backward B (input-grad) and W
        (weight-grad) jobs, W deferred into cooldown bubbles (parity:
        passes/pipeline_scheduler_pass/pipeline_zero_bubble.py). Grads
        accumulate into the live parameters' grad buffers, so the normal
        optimizer.step() applies them.

        Determinism note: each stage function pins the RNG state captured
        at batch start, so the B/W recomputation linearizes the same
        forward (the reference preserves RNG per micro-batch the same
        way); dropout masks therefore repeat across micro-batches inside
        one ZB batch."""
        import jax
        from ...core import autograd
        from ...core.tensor import Tensor
        from ...framework import random as _random
        from ..fleet_executor import ZeroBubbleRunner

        n_stages = len(self._layers.segment_parts) - 1
        rng_state = _random.get_rng_state()

        def make_stage(stage_layers):
            tensors = {}
            for li, layer in enumerate(stage_layers):
                for name, t in layer.state_dict().items():
                    tensors[f"{li}.{name}"] = t
            params0 = {k: t._data for k, t in tensors.items()}

            def fn(params, x):
                _random.set_rng_state(rng_state)
                saved = {k: t._data for k, t in tensors.items()}
                try:
                    with autograd.no_grad():
                        for k, t in tensors.items():
                            t._data = params[k]
                        h = Tensor(x)
                        for layer in stage_layers:
                            h = layer(h)
                        return h._data
                finally:
                    for k, t in tensors.items():
                        t._data = saved[k]

            return fn, params0, tensors

        stages = [make_stage(self._layers.get_stage_layers(s))
                  for s in range(n_stages)]
        stage_fns = [s[0] for s in stages]
        stage_params = [s[1] for s in stages]

        def loss_fn(pred, label):
            with autograd.no_grad():
                l = self._layers.loss(Tensor(pred), Tensor(label))
                if scaler is not None:
                    l = scaler.scale(l)
                return l._data

        # jit_stages=False: a fresh runner (fresh stage closures — they
        # capture this batch's RNG state) is built per batch, so jitted
        # jobs could never reuse their cache and every step would pay a
        # full retrace+compile; the compiled measured path is
        # ThreadedFleetExecutor/tools/bench_pipeline.py. ZB-V requires an
        # even stage-segment count (2 chunks per rank).
        sched = "ZB-H1" if self._schedule == "ZBH1" else self._schedule
        runner = ZeroBubbleRunner(stage_fns, stage_params, loss_fn,
                                  schedule=sched, jit_stages=False)
        xs = [m[0]._data for m in micros]
        ys = [m[1]._data for m in micros]
        mean_loss, grads = runner.run(xs, ys)
        n = len(micros)
        for (fn, params0, tensors), g in zip(stages, grads):
            if g is None:
                continue
            for k, t in tensors.items():
                gk = g[k] / n
                t._grad_buffer = gk if t._grad_buffer is None \
                    else t._grad_buffer + gk
        for cb in self._step_callbacks:
            cb(n - 1)
        import jax.numpy as jnp
        return Tensor(jnp.asarray(mean_loss))

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: pipeline_parallel.py:810."""
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        from ...core.autograd import no_grad
        micros = self._split_micro(data)
        total = None
        with no_grad():
            for x, y in micros:
                out = self._layers.forward(x)
                loss = self._layers.loss(out, y) if compute_loss else out
                total = loss if total is None else total + loss
        return total / len(micros)
