"""Hybrid-parallel model wrappers.

Parity: reference `fleet/meta_parallel/` — `TensorParallel` (tensor_parallel.
py:28), `ShardingParallel`, `SegmentParallel` (segment_parallel.py:26),
`PipelineParallel` with FThenB / 1F1B micro-batch schedules
(pipeline_parallel.py:245,565,2018).

TPU-native notes: parameter broadcast/sync at wrap time is a no-op in
single-process SPMD (one copy of truth); gradient synchronization happens
either via GSPMD (sharded batch axis) or explicitly in-trace. The PP
wrapper here provides the reference's micro-batch semantics (gradient
accumulation with schedule-ordered fwd/bwd); the throughput-oriented
in-graph pipeline (shard_map + ppermute over the 'pipe' axis) lives in
distributed.pipeline and is used by the model recipes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from .pp_layers import PipelineLayer

__all__ = ["TensorParallel", "ShardingParallel", "SegmentParallel",
           "PipelineParallel"]


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class TensorParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/tensor_parallel.py:28."""


class ShardingParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/sharding_parallel.py."""


class SegmentParallel(_MetaParallelBase):
    """Sequence/segment parallel wrapper (parity: segment_parallel.py:26).
    Activations are sharded along the sequence dim over the 'sep' axis;
    attention uses all-to-all (Ulysses) via the sp utilities."""


class PipelineParallel(_MetaParallelBase):
    """Parity: fleet/meta_parallel/pipeline_parallel.py (train_batch:810,
    forward_backward_pipeline 1F1B:565)."""

    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel needs a PipelineLayer")
        super().__init__(layers, hcg, strategy)
        pcfg = strategy.pipeline_configs if strategy else {}
        self._micro_batch_size = pcfg.get("micro_batch_size", 1)
        self._accumulate_steps = pcfg.get("accumulate_steps", 1)
        self._schedule = pcfg.get("schedule_mode", "1F1B")
        self._step_callbacks = []

    def register_micro_step_callback(self, fn):
        """Parity: pipeline_parallel.py:166 micro-batch step callbacks."""
        self._step_callbacks.append(fn)

    def _split_micro(self, data):
        from ...ops.manipulation import split
        x, y = data
        n = self._accumulate_steps
        xs = split(x, n, axis=0) if n > 1 else [x]
        ys = split(y, n, axis=0) if n > 1 else [y]
        return list(zip(xs, ys))

    def forward_backward_pipeline(self, data, scaler=None):
        """Micro-batch schedule. On TPU every 'rank' sees the whole graph
        (SPMD); the 1F1B ordering is realized for memory by interleaving
        fwd/bwd over micro-batches — backward for micro i is issued as soon
        as its forward completes in the steady state."""
        micros = self._split_micro(data)
        total = None
        n = len(micros)
        warmup = min(self._hcg.get_pipe_parallel_world_size() - 1, n) \
            if self._schedule == "1F1B" else n
        pending = []

        def fwd(mb):
            x, y = mb
            out = self._layers.forward(x)
            loss = self._layers.loss(out, y)
            if scaler is not None:
                loss_s = scaler.scale(loss)
            else:
                loss_s = loss
            return loss, loss_s

        def bwd(loss_s):
            (loss_s / n).backward()

        # warmup forwards
        for i in range(warmup):
            pending.append(fwd(micros[i]))
        # steady 1F1B
        for i in range(warmup, n):
            loss, loss_s = pending.pop(0)
            total = loss.detach() if total is None else total + loss.detach()
            bwd(loss_s)
            pending.append(fwd(micros[i]))
            for cb in self._step_callbacks:
                cb(i)
        # cooldown
        for loss, loss_s in pending:
            total = loss.detach() if total is None else total + loss.detach()
            bwd(loss_s)
        return total / n if total is not None else None

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: pipeline_parallel.py:810."""
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        from ...core.autograd import no_grad
        micros = self._split_micro(data)
        total = None
        with no_grad():
            for x, y in micros:
                out = self._layers.forward(x)
                loss = self._layers.loss(out, y) if compute_loss else out
                total = loss if total is None else total + loss
        return total / len(micros)
