"""Model-parallel (TP) layers + TP RNG tracker.

Parity: reference `python/paddle/distributed/fleet/layers/mpu/`
(mp_layers.py: VocabParallelEmbedding:47, ColumnParallelLinear:334,
RowParallelLinear:541, ParallelCrossEntropy:742; mp_ops.py c_identity/
c_split/mp_allreduce PyLayers; random.py RNGStatesTracker:34).

TPU-native: instead of explicit c_* collective ops, weights carry a
NamedSharding over the 'model' mesh axis and forwards place GSPMD sharding
constraints; XLA inserts the all_gather/psum on ICI exactly where the
reference issues NCCL calls. The explicit-collective formulation remains
available through shard_map when the 'model' axis is bound (see
distributed.collective).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn.initializer import Constant, Normal, XavierUniform
from ...nn.layer.layers import Layer
from ...ops.dispatch import apply_op
from ...framework.random import RNGState

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "RNGStatesTracker",
           "get_rng_state_tracker", "mark_sharding", "current_mesh",
           "mesh_scope"]

MODEL_AXIS = "model"

# Explicit mesh overrides (innermost wins) consulted by current_mesh()
# BEFORE the fleet singleton: a TP ServingEngine activates its own mesh
# around program tracing without going through fleet.init (which owns
# the process-global hybrid topology — a serving process may legally
# host engines of different TP degrees side by side).
_mesh_stack: list = []


class mesh_scope:
    """Context manager pinning current_mesh() to `mesh` for the scope's
    duration. Nestable; `mesh_scope(None)` masks any outer mesh (the
    constraints become no-ops inside)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _mesh_stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _mesh_stack.pop()
        return False


def current_mesh():
    """The active hybrid mesh: the innermost `mesh_scope` override if
    one is live, else the fleet.init singleton, else None."""
    if _mesh_stack:
        return _mesh_stack[-1]
    from . import fleet as fleet_mod
    hcg = fleet_mod._hcg
    return hcg.mesh if hcg is not None else None


def _constraint(arr, spec):
    """Apply a GSPMD sharding constraint if we're under a mesh-aware trace."""
    mesh = current_mesh()
    if mesh is None or isinstance(arr, (int, float)):
        return arr
    try:
        return jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))
    except Exception:
        return arr


def mark_sharding(param: Tensor, spec: P):
    """Place a parameter according to spec on the hybrid mesh (device_put now
    if mesh is live; always record intent for the pjit path)."""
    param._spec = spec
    mesh = current_mesh()
    if mesh is not None:
        try:
            param._data = jax.device_put(param._data, NamedSharding(mesh, spec))
        except Exception:
            pass
    return param


class RNGStatesTracker:
    """Named RNG streams so TP ranks can draw either identical (replicated
    init) or axis-distinct (dropout inside TP region) randomness.
    Parity: fleet/layers/mpu/random.py:34."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def add(self, name, seed):
        if name in self.states_:
            raise ValueError(f"rng state {name} already exists")
        self.states_[name] = RNGState(int(seed))

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    class _Guard:
        def __init__(self, tracker, name):
            self.tracker, self.name = tracker, name

        def __enter__(self):
            from ...framework import random as _r
            self._saved = _r._global
            _r._global = self.tracker.states_[self.name]
            return self

        def __exit__(self, *a):
            from ...framework import random as _r
            _r._global = self._saved
            return False

    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states_:
            self.add(name, 0)
        return RNGStatesTracker._Guard(self, name)


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the model axis.
    Parity: mp_layers.py:47 (c_embedding kernel + allreduce); GSPMD emits
    the same gather+psum from the sharded jnp.take."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform())
        mark_sharding(self.weight, P(MODEL_AXIS, None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return apply_op("vp_embedding_out", lambda a: _constraint(a, P()), out)


class ColumnParallelLinear(Layer):
    """Linear with output dim sharded over model axis.
    Parity: mp_layers.py:334."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        mark_sharding(self.weight, P(None, MODEL_AXIS))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            mark_sharding(self.bias, P(MODEL_AXIS))
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        spec = P() if self.gather_output else \
            P(*([None] * (out.ndim - 1) + [MODEL_AXIS]))
        return apply_op("col_parallel_out", lambda a: _constraint(a, spec), out)


class RowParallelLinear(Layer):
    """Linear with input dim sharded over model axis; output is psum-reduced.
    Parity: mp_layers.py:541."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        mark_sharding(self.weight, P(MODEL_AXIS, None))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            mark_sharding(self.bias, P())
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = apply_op(
                "row_parallel_in",
                lambda a: _constraint(
                    a, P(*([None] * (a.ndim - 1) + [MODEL_AXIS]))), x)
        out = F.linear(x, self.weight, None)
        out = apply_op("row_parallel_out", lambda a: _constraint(a, P()), out)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Softmax-CE over a class dim sharded on the model axis.
    Parity: mp_layers.py:742 (c_softmax_with_cross_entropy). GSPMD keeps the
    logits sharded and reduces the log-sum-exp over ICI."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        def _f(logits, lab):
            lab = lab.astype(jnp.int32)
            if lab.ndim == logits.ndim:
                lab = jnp.squeeze(lab, -1)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            valid = lab != self.ignore_index
            safe = jnp.where(valid, lab, 0)
            picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            loss = jnp.where(valid, -picked, 0.0)
            return loss[..., None]
        return apply_op("parallel_cross_entropy", _f, input, label)
