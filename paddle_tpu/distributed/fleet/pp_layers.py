"""PipelineLayer: stage-partitionable model description.

Parity: reference `python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py` (LayerDesc:56, SharedLayerDesc:76,
PipelineLayer:257 with uniform/cost segmentation).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ...nn.layer.layers import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (e.g. embedding +
    output head). Parity: pp_layers.py:76."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Parity: pp_layers.py:257. Builds only this stage's layers when a
    topology is provided; single-process SPMD mode builds all stages and the
    stage structure drives the in-graph pipeline executor
    (distributed.pipeline)."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._seg_method = seg_method
        self._shared_layers = {}

        self.segment_parts = self._segment(len(self._layers_desc),
                                           self._num_stages, seg_method)
        # SPMD single-process: materialize every stage (sharding over the
        # 'pipe' axis happens at the array level, not by owning a subset).
        from ...nn.layer.container import LayerList
        built = []
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared_layers:
                    built.append(_SharedRef(
                        self._shared_layers[desc.layer_name], desc.forward_func))
                    continue
                layer = desc.build_layer()
                self._shared_layers[desc.layer_name] = layer
                built.append(layer)
            elif isinstance(desc, LayerDesc):
                built.append(desc.build_layer())
            elif isinstance(desc, Layer):
                built.append(desc)
            elif callable(desc):
                built.append(_FnLayer(desc))
            else:
                raise TypeError(f"bad pipeline entry {desc!r}")
        self.run_function = LayerList(built)

    @staticmethod
    def _segment(n_layers, n_stages, method):
        """Uniform (or 'layer:'-prefix cost) segmentation -> stage boundaries.
        Parity: SegmentLayers in pp_layers.py:92."""
        base = n_layers // n_stages
        extra = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < extra else 0))
        return bounds

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x, **kwargs):
        for layer in self.run_function:
            x = layer(x)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            return output
        return self._loss_fn(output, label)


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _SharedRef(Layer):
    """Second occurrence of a SharedLayerDesc: reuses the first build's
    parameters (weight tying)."""

    def __init__(self, target, forward_func):
        super().__init__()
        self._target_ref = [target]  # avoid registering as sublayer (no dup)
        self._forward_func = forward_func

    def forward(self, x):
        target = self._target_ref[0]
        if self._forward_func is not None:
            return self._forward_func(target, x)
        return target(x)
