"""The fleet facade.

Parity: reference `python/paddle/distributed/fleet/fleet.py:218,674`
(fleet.init -> hybrid env; distributed_model; distributed_optimizer) and
`fleet/model.py:32,134-153` (wrapper selection by degrees).
"""
from __future__ import annotations

from typing import Optional

import jax

from ...core.tensor import Tensor
from ..env import get_rank, get_world_size, init_parallel_env
from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["init", "is_initialized", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "fleet"]

_strategy: Optional[DistributedStrategy] = None
_hcg: Optional[HybridCommunicateGroup] = None


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """Parity: fleet.init. Builds the hybrid topology over jax devices."""
    global _strategy, _hcg
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    h = _strategy.hybrid_configs
    topo = CommunicateTopology(
        ["data", "pipe", "sharding", "sep", "model"],
        [h["dp_degree"], h["pp_degree"], h["sharding_degree"],
         h["sep_degree"], h["mp_degree"]])
    _hcg = HybridCommunicateGroup(topo, rank=get_rank())
    return fleet


def is_initialized():
    return _hcg is not None


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _hcg is None:
        init()
    return _hcg


def _ensure_init():
    if _hcg is None:
        init()


def distributed_model(model):
    """Parity: fleet.distributed_model (fleet/model.py:32): wrap by degrees."""
    _ensure_init()
    from ..parallel import DataParallel
    from .meta_parallel import (PipelineParallel, ShardingParallel,
                                TensorParallel)
    from .pp_layers import PipelineLayer
    if _hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError("pipeline parallel requires a PipelineLayer model "
                            "(parity: reference fleet/model.py:118)")
        return PipelineParallel(model, _hcg, _strategy)
    if _hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, _hcg, _strategy)
    if _hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, _hcg, _strategy)
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Parity: fleet.distributed_optimizer -> HybridParallelOptimizer.
    An explicit strategy argument overrides the fleet.init one (the
    reference accepts either call pattern)."""
    _ensure_init()
    from .hybrid_parallel_optimizer import HybridParallelOptimizer
    if strategy is not None and _strategy is not None:
        a = getattr(strategy, "hybrid_configs", None)
        b = getattr(_strategy, "hybrid_configs", None)
        if a and b and dict(a) != dict(b):
            raise ValueError(
                "distributed_optimizer strategy.hybrid_configs "
                f"{a} differ from the fleet.init topology {b}; the comm "
                "groups were built at init — re-run fleet.init with the "
                "new topology instead")
    return HybridParallelOptimizer(optimizer, _hcg,
                                   strategy if strategy is not None
                                   else _strategy)


def collective_perf(comm_type="allreduce", round=5, size_and_time=None):
    """Parity: fleet.collective_perf (fleet.py:632) — micro-bench of a
    collective over the live mesh (or a no-op report on one device)."""
    import time
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _hcg.mesh if _hcg else None
    results = {}
    sizes = list((size_and_time or {1 << 20: None}).keys())
    for size in sizes:
        n = size // 4
        x = jnp.ones((max(n, 8),), jnp.float32)
        if mesh is not None and mesh.devices.size > 1:
            try:
                from jax import shard_map
            except ImportError:  # older jax: experimental
                from ...jax_compat import shard_map
            f = jax.jit(shard_map(lambda a: jax.lax.psum(a, "data"),
                                  mesh=mesh,
                                  in_specs=P("data"), out_specs=P()))
            xs = jax.device_put(
                jnp.ones((mesh.shape["data"] * max(n // 8, 8),), jnp.float32),
                NamedSharding(mesh, P("data")))
            f(xs).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(round):
                f(xs).block_until_ready()
            dt = (time.perf_counter() - t0) / round
        else:
            t0 = time.perf_counter()
            for _ in range(round):
                (x + 1).block_until_ready()
            dt = (time.perf_counter() - t0) / round
        results[size] = dt
        print(f"[collective_perf] {comm_type} size={size}B "
              f"avg={dt*1e6:.1f}us")
    return results


class UtilBase:
    """Parity: fleet.UtilBase (base/util_factory.py) — cross-worker
    utility helpers riding the collective layer + local FS."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        import jax.numpy as jnp
        from ..collective import ReduceOp, all_reduce as _ar
        from ...core.tensor import Tensor
        t = input if isinstance(input, Tensor) else Tensor(jnp.asarray(
            np.asarray(input)))
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        _ar(t, op=op)
        return np.asarray(t._data)

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _b
        _b()

    def all_gather(self, input, comm_world="worker"):
        out = []
        import numpy as np
        import jax.numpy as jnp
        from ..collective import all_gather as _ag
        from ...core.tensor import Tensor
        _ag(out, Tensor(jnp.asarray(np.asarray(input))))
        return [np.asarray(t._data) for t in out]

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference contract:
        earlier workers take the remainder)."""
        from ..env import get_rank, get_world_size
        n, rank = max(get_world_size(), 1), get_rank()
        per, rem = divmod(len(files), n)
        start = rank * per + min(rank, rem)
        return files[start:start + per + (1 if rank < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)


util = UtilBase()


class _FleetNamespace:
    """`fleet` object surface (so `from paddle_tpu.distributed import fleet`
    followed by fleet.init(...) works like the reference)."""
    init = staticmethod(init)
    is_initialized = staticmethod(is_initialized)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)
    collective_perf = staticmethod(collective_perf)
    DistributedStrategy = DistributedStrategy

    @property
    def worker_num(self):
        return get_world_size()

    @property
    def worker_index(self):
        return get_rank()

    @property
    def util(self):
        return util


# reference exports the class as fleet.Fleet (fleet.py:218)
Fleet = _FleetNamespace

fleet = _FleetNamespace()
