"""fleet_executor: multi-program Plan/Job scheduling.

Parity: reference `paddle/fluid/distributed/fleet_executor/` — the
actor-style pipeline runtime executing a `Plan` of `Job`s (forward /
backward / optimizer sub-programs per micro-batch, produced by the
pipeline_scheduler passes, `new_executor/interpreter/plan.h`) with
interceptors exchanging messages.

TPU-native: a Job wraps a compiled callable (TracedFunction or plain fn)
instead of a ProgramDesc; the FleetExecutor sequences jobs per the
schedule (FThenB / 1F1B orderings from PipelineMicroScheduler). The
*performance* pipeline path remains distributed.pipeline (one fused XLA
program with ppermute edges); this executor exists for the multi-program
orchestration capability — heterogeneous jobs, per-micro-batch callbacks,
cross-program state carried host-side.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .pipeline import PipelineMicroScheduler, ZB_SCHEDULES

__all__ = ["Job", "Plan", "FleetExecutor", "build_pipeline_plan"]


class Job:
    """Parity: interpreter Plan's Job (type + micro_batch id)."""

    def __init__(self, type: str, fn: Callable = None, micro_batch_id=-1):
        self._type = type
        self._fn = fn
        self._micro_batch_id = micro_batch_id

    def type(self):
        return self._type

    def micro_batch_id(self):
        return self._micro_batch_id

    def set_micro_batch_id(self, i):
        self._micro_batch_id = i

    def run(self, *args, **kwargs):
        if self._fn is None:
            return None
        return self._fn(*args, **kwargs)

    def __repr__(self):
        return f"Job({self._type}, mb={self._micro_batch_id})"


class Plan:
    """Parity: interpreter/plan.h Plan — an ordered list of typed jobs."""

    def __init__(self, job_list: List[Job],
                 type_to_program: Optional[Dict[str, Callable]] = None):
        self._jobs = list(job_list)
        self._type_to_program = dict(type_to_program or {})
        for j in self._jobs:
            if j._fn is None and j.type() in self._type_to_program:
                j._fn = self._type_to_program[j.type()]

    def job_list(self):
        return list(self._jobs)

    def micro_batch_num(self):
        return 1 + max((j.micro_batch_id() for j in self._jobs), default=0)


class FleetExecutor:
    """Sequences a Plan's jobs (parity: fleet_executor.h FleetExecutor +
    Carrier; the message-bus actor machinery collapses to a host loop since
    every job runs in this process against the XLA runtime)."""

    def __init__(self, plan: Plan):
        self._plan = plan
        self._callbacks: List[Callable] = []

    def register_micro_batch_callback(self, cb: Callable):
        """Parity: micro-batch step callbacks
        (pipeline_parallel.py:166)."""
        self._callbacks.append(cb)

    def run(self, feeds: Optional[Dict[int, Any]] = None):
        """Run every job in order. `feeds` maps micro_batch_id -> job
        input; returns {micro_batch_id: last output per micro batch}."""
        feeds = feeds or {}
        results: Dict[int, Any] = {}
        for job in self._plan.job_list():
            mb = job.micro_batch_id()
            arg = feeds.get(mb)
            out = job.run(arg) if arg is not None else job.run()
            if out is not None:
                results[mb] = out
            for cb in self._callbacks:
                cb(job.type(), mb)
        return results


def build_pipeline_plan(forward_fn, backward_fn, opt_fn, n_micro,
                        n_stages=1, schedule="1F1B", weight_grad_fn=None):
    """Build a Plan from the 1F1B / FThenB / ZB-H1 micro-batch orderings
    (parity: pipeline_scheduler_pass building multi-Job plans,
    passes/pipeline_scheduler_pass/pipeline_1f1b.py:39,
    pipeline_zero_bubble.py:62 — ZB-H1 splits backward into input-grad
    'backward_b' and deferred weight-grad 'backward_w' jobs)."""
    sched = PipelineMicroScheduler(n_stages=n_stages, n_micro=n_micro,
                                   schedule=schedule)
    zb = schedule in ZB_SCHEDULES
    if zb and weight_grad_fn is None:
        raise ValueError(
            "zero-bubble schedules defer weight grads into backward_w "
            "jobs: pass weight_grad_fn (a silent no-op would train "
            "without weight gradients)")
    jobs = []
    for ev in sched.steps():
        kind, mb = ev
        if kind == "F":
            jobs.append(Job("forward", forward_fn, mb))
        elif kind == "W":
            jobs.append(Job("backward_w", weight_grad_fn, mb))
        else:
            jobs.append(Job("backward_b" if zb else "backward",
                            backward_fn, mb))
    jobs.append(Job("optimizer", opt_fn))
    return Plan(jobs)
