"""fleet_executor: multi-program Plan/Job scheduling.

Parity: reference `paddle/fluid/distributed/fleet_executor/` — the
actor-style pipeline runtime executing a `Plan` of `Job`s (forward /
backward / optimizer sub-programs per micro-batch, produced by the
pipeline_scheduler passes, `new_executor/interpreter/plan.h`) with
interceptors exchanging messages.

TPU-native: a Job wraps a compiled callable (TracedFunction or plain fn)
instead of a ProgramDesc; the FleetExecutor sequences jobs per the
schedule (FThenB / 1F1B orderings from PipelineMicroScheduler). The
*performance* pipeline path remains distributed.pipeline (one fused XLA
program with ppermute edges); this executor exists for the multi-program
orchestration capability — heterogeneous jobs, per-micro-batch callbacks,
cross-program state carried host-side.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .pipeline import PipelineMicroScheduler, ZB_SCHEDULES, ZBV_SCHEDULES

__all__ = ["Job", "Plan", "FleetExecutor", "build_pipeline_plan",
           "ZeroBubbleRunner", "simulate_pipeline_makespan",
           "per_rank_schedule", "ThreadedFleetExecutor",
           "ThreadedZBVExecutor", "zbv_stage_of",
           "build_zbv_rank_schedules", "zb_dispatch_tax_model",
           "choose_pipeline_schedule", "PIPE_PID"]

# chrome-trace pid for pipeline-rank tracks (serving request rows use 1,
# training steps 2, profiler host spans os.getpid())
PIPE_PID = 3


class Job:
    """Parity: interpreter Plan's Job (type + micro_batch id)."""

    def __init__(self, type: str, fn: Callable = None, micro_batch_id=-1):
        self._type = type
        self._fn = fn
        self._micro_batch_id = micro_batch_id

    def type(self):
        return self._type

    def micro_batch_id(self):
        return self._micro_batch_id

    def set_micro_batch_id(self, i):
        self._micro_batch_id = i

    def run(self, *args, **kwargs):
        if self._fn is None:
            return None
        return self._fn(*args, **kwargs)

    def __repr__(self):
        return f"Job({self._type}, mb={self._micro_batch_id})"


class Plan:
    """Parity: interpreter/plan.h Plan — an ordered list of typed jobs."""

    def __init__(self, job_list: List[Job],
                 type_to_program: Optional[Dict[str, Callable]] = None):
        self._jobs = list(job_list)
        self._type_to_program = dict(type_to_program or {})
        for j in self._jobs:
            if j._fn is None and j.type() in self._type_to_program:
                j._fn = self._type_to_program[j.type()]

    def job_list(self):
        return list(self._jobs)

    def micro_batch_num(self):
        return 1 + max((j.micro_batch_id() for j in self._jobs), default=0)


class FleetExecutor:
    """Sequences a Plan's jobs (parity: fleet_executor.h FleetExecutor +
    Carrier; the message-bus actor machinery collapses to a host loop since
    every job runs in this process against the XLA runtime)."""

    def __init__(self, plan: Plan):
        self._plan = plan
        self._callbacks: List[Callable] = []

    def register_micro_batch_callback(self, cb: Callable):
        """Parity: micro-batch step callbacks
        (pipeline_parallel.py:166)."""
        self._callbacks.append(cb)

    def run(self, feeds: Optional[Dict[int, Any]] = None):
        """Run every job in order. `feeds` maps micro_batch_id -> job
        input; returns {micro_batch_id: last output per micro batch}."""
        feeds = feeds or {}
        results: Dict[int, Any] = {}
        for job in self._plan.job_list():
            mb = job.micro_batch_id()
            arg = feeds.get(mb)
            out = job.run(arg) if arg is not None else job.run()
            if out is not None:
                results[mb] = out
            for cb in self._callbacks:
                cb(job.type(), mb)
        return results


def build_pipeline_plan(forward_fn, backward_fn, opt_fn, n_micro,
                        n_stages=1, schedule="1F1B", weight_grad_fn=None):
    """Build a Plan from the 1F1B / FThenB / ZB-H1 micro-batch orderings
    (parity: pipeline_scheduler_pass building multi-Job plans,
    passes/pipeline_scheduler_pass/pipeline_1f1b.py:39,
    pipeline_zero_bubble.py:62 — ZB-H1 splits backward into input-grad
    'backward_b' and deferred weight-grad 'backward_w' jobs)."""
    sched = PipelineMicroScheduler(n_stages=n_stages, n_micro=n_micro,
                                   schedule=schedule)
    zb = schedule in ZB_SCHEDULES or schedule in ZBV_SCHEDULES
    if zb and weight_grad_fn is None:
        raise ValueError(
            "zero-bubble schedules defer weight grads into backward_w "
            "jobs: pass weight_grad_fn (a silent no-op would train "
            "without weight gradients)")
    jobs = []
    for ev in sched.steps():
        kind, mb = ev
        if kind == "F":
            jobs.append(Job("forward", forward_fn, mb))
        elif kind == "W":
            jobs.append(Job("backward_w", weight_grad_fn, mb))
        else:
            jobs.append(Job("backward_b" if zb else "backward",
                            backward_fn, mb))
    jobs.append(Job("optimizer", opt_fn))
    return Plan(jobs)


class ZeroBubbleRunner:
    """EXECUTES the ZB-H1 schedule with the backward truly split
    (VERDICT r2 missing #2: the schedule used to be bookkeeping only).

    Parity: reference passes/pipeline_scheduler_pass/pipeline_zero_bubble.py
    :62,151 — the pass splits each matmul's grad into an input-grad op
    (backward_b, critical path: its cotangent feeds the upstream stage)
    and a weight-grad op (backward_w, deferrable: depends only on saved
    activations + saved cotangents, so it slides into cooldown bubbles).

    TPU-native split: per stage, `jax.vjp(lambda x: fn(params, x))` gives
    the dx pullback alone (B job) and `jax.vjp(lambda p: fn(p, x))` the
    dw pullback alone (W job). The W job reads only `(saved activation,
    saved cotangent)` — proof of deferrability is that running it at the
    Plan's (late) position yields bit-identical weight grads to fused
    autograd (tested). Each split pullback re-linearizes its forward
    (recompute), the same trade remat already makes.
    """

    def __init__(self, stage_fns, stage_params, loss_fn,
                 schedule: str = "ZB-H1", jit_stages: bool = True):
        import jax
        self.stage_fns = list(stage_fns)   # materialize before validating
        if schedule not in ZB_SCHEDULES and schedule not in ZBV_SCHEDULES:
            # (ADVICE r3) a non-ZB schedule emits plain 'backward' jobs
            # this runner does not re-wrap — fail loudly instead of a
            # TypeError deep inside FleetExecutor.run
            raise ValueError(
                f"ZeroBubbleRunner only executes zero-bubble schedules "
                f"{ZB_SCHEDULES + ZBV_SCHEDULES}, got {schedule!r}; use "
                f"FleetExecutor with build_pipeline_plan for 1F1B/FThenB")
        if schedule in ZBV_SCHEDULES and len(self.stage_fns) % 2:
            raise ValueError(
                "ZB-V places 2 chunks per rank: pass an even number of "
                "virtual stage fns (got %d)" % len(self.stage_fns))
        self._jax = jax
        self.stage_params = list(stage_params)
        self.loss_fn = loss_fn
        self.schedule = schedule
        self.n_stages = len(self.stage_fns)
        # Compiled job bodies (VERDICT r3 weak #5: the executed ZB path was
        # un-jitted per-op eager dispatch). Each stage's forward, dx
        # pullback and dw pullback compile once and are reused across
        # micro-batches; jax caches by (shape, dtype) thereafter.
        self._jit = bool(jit_stages)
        if self._jit:
            import jax.numpy as jnp

            def make_jobs(fn):
                fwd = jax.jit(fn)
                dx = jax.jit(lambda p, x, g, fn=fn:
                             jax.vjp(lambda xx: fn(p, xx), x)[1](g)[0])
                dw = jax.jit(lambda p, x, g, fn=fn:
                             jax.vjp(lambda pp: fn(pp, x), p)[1](g)[0])
                return fwd, dx, dw

            jobs = [make_jobs(f) for f in self.stage_fns]
            self._fwd_jit = [j[0] for j in jobs]
            self._dx_jit = [j[1] for j in jobs]
            self._dw_jit = [j[2] for j in jobs]

            def loss_grad(y, label):
                loss, pull = jax.vjp(lambda yy: loss_fn(yy, label), y)
                (g,) = pull(jnp.ones_like(loss))
                return loss, g

            self._loss_grad_jit = jax.jit(loss_grad)
        # per-microbatch saved state
        self._acts: Dict[int, list] = {}     # m -> [x_s per stage]
        self._cots: Dict[int, list] = {}     # m -> [dL/dy_s per stage]
        self._preds: Dict[int, Any] = {}
        self.grads = [None] * self.n_stages  # accumulated weight grads
        self.losses: List[float] = []
        self.job_trace: List[str] = []

    # -- jobs ---------------------------------------------------------------
    def _forward(self, m, x):
        acts = []
        for s, (fn, p) in enumerate(zip(self.stage_fns, self.stage_params)):
            acts.append(x)
            x = self._fwd_jit[s](p, x) if self._jit else fn(p, x)
        self._acts[m] = acts
        self._preds[m] = x
        self.job_trace.append(f"F{m}")
        return x

    def _backward_b(self, m, label):
        """Input-grad (dx) chain: the critical path. Saves each stage's
        incoming cotangent for the deferred W job; computes NO weight
        grads."""
        jax = self._jax
        if self._jit:
            loss, g = self._loss_grad_jit(self._preds[m], label)
        else:
            loss, pull = jax.vjp(lambda y: self.loss_fn(y, label),
                                 self._preds[m])
            (g,) = pull(jax.numpy.ones_like(loss))
        cots = [None] * self.n_stages
        for s in range(self.n_stages - 1, -1, -1):
            cots[s] = g
            if s > 0:       # stage 0's dx goes nowhere (data input)
                fn, p, x = self.stage_fns[s], self.stage_params[s], \
                    self._acts[m][s]
                if self._jit:
                    g = self._dx_jit[s](p, x, g)
                else:
                    _, pull_x = jax.vjp(lambda xx: fn(p, xx), x)
                    (g,) = pull_x(g)
        self._cots[m] = cots
        self.losses.append(float(loss))
        self.job_trace.append(f"B{m}")

    def _backward_w(self, m):
        """Weight-grad job: reads only saved (activation, cotangent) —
        runnable any time after B(m), which is what lets the schedule
        park it in a bubble."""
        jax = self._jax
        for s in range(self.n_stages):
            fn, x = self.stage_fns[s], self._acts[m][s]
            if self._jit:
                dW = self._dw_jit[s](self.stage_params[s], x,
                                     self._cots[m][s])
            else:
                _, pull_p = jax.vjp(lambda pp: fn(pp, x),
                                    self.stage_params[s])
                (dW,) = pull_p(self._cots[m][s])
            self.grads[s] = dW if self.grads[s] is None else \
                jax.tree_util.tree_map(lambda a, b: a + b,
                                       self.grads[s], dW)
        # free the per-microbatch buffers (the memory point of ZB: W
        # retires the saved state, exactly like the reference's
        # backward_w ops releasing their inputs)
        del self._acts[m], self._cots[m], self._preds[m]
        self.job_trace.append(f"W{m}")

    def run(self, micro_inputs, micro_labels, opt_fn=None):
        """Build the ZB Plan for these micro-batches and execute it on the
        FleetExecutor. Returns (mean loss, accumulated grads per stage)."""
        n_micro = len(micro_inputs)
        plan = build_pipeline_plan(
            forward_fn=lambda m: self._forward(m, micro_inputs[m]),
            backward_fn=lambda m: self._backward_b(m, micro_labels[m]),
            weight_grad_fn=self._backward_w,
            opt_fn=opt_fn or (lambda: None),
            n_micro=n_micro, n_stages=self.n_stages,
            schedule=self.schedule)
        # jobs take their micro-batch id as the sole argument
        for job in plan.job_list():
            if job.type() in ("forward", "backward_b", "backward_w"):
                mb = job.micro_batch_id()
                fn = job._fn
                job._fn = (lambda fn=fn, mb=mb: fn(mb))
        FleetExecutor(plan).run()
        mean_loss = sum(self.losses[-n_micro:]) / n_micro
        return mean_loss, self.grads


class _ThreadedPipelineBase:
    """Shared per-rank-thread machinery for the measured pipeline
    executors: dependency events, per-job timing (waits excluded),
    error fan-out, join/alive detection, per-kind durations.

    Subclass contract:
      _n_workers() -> int
      _worker_rows(r) -> iterable of schedule rows for rank r
      _event_key(r, row) -> (kind, micro, stage) event key
      _prepare_job(r, row, ctx, wait) -> zero-arg compute thunk; performs
          its dependency waits + input fetches BEFORE returning so only
          the compute lands in the timeline. ctx = {acts, cots, inputs,
          labels} shared stores.
    """

    timeline: Dict[tuple, tuple]
    errors: List[BaseException]

    def run(self, micro_inputs, micro_labels, timeout=300.0):
        """Execute all ranks concurrently; returns the wall-clock
        makespan in seconds (first job start -> last job end)."""
        import threading
        import time

        self.timeline = {}   # reentrant: drop any previous run's spans
        self._key_rank = {}  # event key -> executing rank (for export)
        self.last_makespan = None
        self.errors = []
        n = self._n_workers()
        events = {self._event_key(r, row): threading.Event()
                  for r in range(n) for row in self._worker_rows(r)}
        ctx = {"acts": {}, "cots": {},
               "inputs": micro_inputs, "labels": micro_labels}

        def wait(key):
            ev = events.get(key)
            if ev is not None and not ev.wait(timeout):
                raise TimeoutError(f"dependency {key} never fired")

        from .. import profiler as _prof

        def worker(r):
            try:
                for row in self._worker_rows(r):
                    key = self._event_key(r, row)
                    thunk = self._prepare_job(r, row, ctx, wait)
                    # pipeline jobs on the profiler timeline, like the
                    # per-op dispatch spans; RecordEvent self-gates on
                    # the tracer and the `with` keeps the device-trace
                    # annotation balanced even when the job raises
                    with _prof.RecordEvent(
                            f"pipe/{key[0]}{key[1]}@s{key[2]}",
                            _prof.TracerEventType.UserDefined):
                        t0 = time.perf_counter()
                        thunk()
                        t1 = time.perf_counter()
                    self.timeline[key] = (t0, t1)
                    self._key_rank[key] = r
                    events[key].set()
            except BaseException as e:  # surface to the caller
                self.errors.append(e)
                for ev in events.values():  # unblock everyone
                    ev.set()

        threads = [threading.Thread(target=worker, args=(r,), daemon=True)
                   for r in range(n)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))
        if any(t.is_alive() for t in threads):
            raise TimeoutError(
                f"pipeline ranks still running after {timeout}s join — "
                "refusing to report a partial makespan")
        if self.errors:
            raise self.errors[0]
        if not self.timeline:
            raise RuntimeError("no jobs executed (empty schedule?)")
        spans = list(self.timeline.values())
        self.last_makespan = (max(t1 for _, t1 in spans)
                              - min(t0 for t0, _ in spans))
        return self.last_makespan

    def measured_durations(self):
        """Mean measured duration per job kind — feed these to the
        dependency model (`simulate_pipeline_makespan` /
        `build_zbv_rank_schedules`) to compare it against the wall
        clock."""
        import statistics
        out = {}
        for kind in ("F", "B", "W"):
            ds = [t1 - t0 for (k, _, _), (t0, t1) in self.timeline.items()
                  if k == kind]
            if ds:
                out[kind] = statistics.mean(ds)
        return out

    # ---- timeline export (ISSUE 12) -------------------------------------
    def chrome_events(self):
        """The measured timeline as chrome-trace events: ONE TRACK PER
        RANK (pid PIPE_PID, tid = rank), F/B/W job spans. Spans were
        stamped with time.perf_counter(), which shares its monotonic
        base with the perf_counter_ns clock `profiler.RecordEvent` and
        the TrainingMonitor use — the export merges with every other
        in-tree chrome trace on ONE timeline."""
        if not self.timeline:
            return []
        evs = [{"name": "process_name", "ph": "M", "pid": PIPE_PID,
                "args": {"name": "pipeline ranks"}}]
        for r in range(self._n_workers()):
            evs.append({"name": "thread_name", "ph": "M", "pid": PIPE_PID,
                        "tid": r, "args": {"name": f"rank {r}"}})
        for key, (t0, t1) in sorted(self.timeline.items(),
                                    key=lambda kv: kv[1][0]):
            kind, m, s = key
            evs.append({"name": f"{kind}{m}", "ph": "X", "cat": "pipeline",
                        "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                        "pid": PIPE_PID,
                        "tid": self._key_rank.get(key, s),
                        "args": {"kind": kind, "micro": m, "stage": s}})
        return evs

    def bubble_report(self):
        """Measured-vs-modeled bubble fractions for the last run():
        measured = 1 - busy/(ranks x makespan) over the recorded spans;
        simulated = the same ratio under the dependency model
        (`simulate_pipeline_makespan` / `build_zbv_rank_schedules`) fed
        the MEASURED mean durations — agreement is the evidence that
        the model's bubble accounting describes this host (the
        BENCH_PIPELINE methodology, now exported per run)."""
        if not self.timeline:
            raise RuntimeError("bubble_report() needs a completed run()")
        spans = list(self.timeline.values())
        makespan = max(t1 for _, t1 in spans) - min(t0 for t0, _ in spans)
        busy = sum(t1 - t0 for t0, t1 in spans)
        workers = self._n_workers()
        durs = self.measured_durations()
        counts = {}
        for (kind, _, _) in self.timeline:
            counts[kind] = counts.get(kind, 0) + 1
        rep = {"workers": workers, "jobs": counts,
               "makespan_s": makespan, "busy_s": busy,
               "bubble_fraction": 1.0 - busy / (workers * makespan)
               if makespan > 0 else None,
               "measured_durations_s": durs,
               "sim_makespan_s": None, "sim_bubble_fraction": None}
        try:
            sim = self._sim_makespan(durs)
        except Exception:
            sim = None
        if sim:
            sim_work = sum(counts.get(k, 0) * durs.get(k, 0.0)
                           for k in counts)
            rep["sim_makespan_s"] = sim
            rep["sim_bubble_fraction"] = 1.0 - sim_work / (workers * sim)
        return rep

    def _sim_makespan(self, durs):   # pragma: no cover - subclass hook
        raise NotImplementedError

    def _schedule_name(self):        # pragma: no cover - subclass hook
        raise NotImplementedError

    def export_timeline(self, path=None, comm=None):
        """One chrome-trace document for the last run(): per-rank job
        tracks + the bubble digest (and an optional `comm` dict — e.g.
        a `TracedFunction.comm_report()` — so the per-rank trace a
        launched job writes carries its collective accounting too).
        `rank` stamps the PROCESS rank (cross-process launches write
        one file per process; tools/dist_report.py merges them)."""
        import json
        import os
        import socket
        from .env import get_rank
        doc = {"displayTimeUnit": "ms",
               "traceEvents": self.chrome_events(),
               "rank": get_rank(),
               # perf_counter bases are per-host: the merger uses this
               # to FLAG cross-host merges instead of pretending one clock
               "host": socket.gethostname(),
               "pipeline": {"schedule": self._schedule_name(),
                            **self.bubble_report()}}
        if comm is not None:
            doc["comm"] = comm
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def export_rank_timelines(self, log_dir=None, comm=None):
        """One chrome-trace file PER RANK under `log_dir` (default:
        $PADDLE_TPU_PROFILER_DIR, else ./profiler_log) — the layout a
        cross-process launched job produces (each process exporting its
        own view), so `make dist-report` / tools/dist_report.py merges
        in-process and cross-process runs identically. Returns the
        written paths."""
        import json
        import os
        from .. import profiler as _profiler
        from .env import get_rank
        d = log_dir or _profiler.default_log_dir()
        os.makedirs(d, exist_ok=True)
        doc = self.export_timeline(comm=comm)
        # global rank = process rank x local worker count + local rank:
        # multi-process launches each exporting an n-worker view get
        # disjoint file names instead of clobbering the overlap
        base = int(get_rank()) * self._n_workers()
        paths = []
        for r in range(self._n_workers()):
            rank_doc = dict(doc)
            rank_doc["rank"] = base + r
            rank_doc["traceEvents"] = [
                e for e in doc["traceEvents"]
                if e.get("ph") != "X" or e.get("tid") == r]
            p = os.path.join(d, f"pipeline_rank{base + r}.json")
            with open(p, "w") as f:
                json.dump(rank_doc, f)
            paths.append(p)
        return paths


class ThreadedFleetExecutor(_ThreadedPipelineBase):
    """Per-rank worker threads executing `per_rank_schedule` event lists
    with cross-rank dependency waits — a MEASURED pipeline makespan, not a
    simulated one (VERDICT r3 weak #5: the bubble-reduction evidence was
    only ever the simulator).

    Parity: the reference fleet executor's Carrier runs one interceptor
    actor per pipeline rank, exchanging activation/cotangent messages
    (`paddle/fluid/distributed/fleet_executor/carrier.cc`); here each rank
    is a thread and the message channel is a {(kind, micro, rank): Event}
    map plus activation/cotangent stores. JAX releases the GIL during
    device execution and each rank's jobs are jitted callables, so stage
    compute genuinely overlaps across ranks (pin each stage's params to
    its own device of the virtual-CPU mesh for true parallelism).

    Job signatures:
      fwd(r, m, x) -> activation            (F job)
      bwd_b(r, m, g_or_label) -> cotangent  (B job; fused backward for
                                             non-ZB schedules)
      bwd_w(r, m) -> None                   (W job, ZB only; accumulates
                                             weight grads rank-locally)
    """

    def __init__(self, n_stages, n_micro, schedule,
                 fwd, bwd_b, bwd_w=None):
        if schedule in ZBV_SCHEDULES:
            raise NotImplementedError(
                "ThreadedFleetExecutor runs one flat stage per rank; the "
                "chunked ZB-V placement lives in ThreadedZBVExecutor — "
                "refusing to silently run ZB-H1 under a V name")
        if schedule in ZB_SCHEDULES and bwd_w is None:
            raise ValueError("ZB schedules need bwd_w (deferred weight "
                             "grads would silently be dropped)")
        self.n_stages, self.n_micro = n_stages, n_micro
        self.schedule = schedule
        self._fwd, self._bwd_b, self._bwd_w = fwd, bwd_b, bwd_w
        self.timeline = {}
        self.errors = []

    def _n_workers(self):
        return self.n_stages

    def _worker_rows(self, r):
        return per_rank_schedule(r, self.n_stages, self.n_micro,
                                 self.schedule)

    def _event_key(self, r, row):
        kind, m = row
        return (kind, m, r)

    def _schedule_name(self):
        return self.schedule

    def _sim_makespan(self, durs):
        # the model's non-ZB backward is the FUSED t_b + t_w; measured
        # fused B spans already carry both, so t_w rides only under ZB
        zb = self.schedule in ZB_SCHEDULES
        return simulate_pipeline_makespan(
            self.n_stages, self.n_micro, self.schedule,
            t_f=durs["F"], t_b=durs["B"],
            t_w=durs.get("W", 0.0) if zb else 0.0)

    def _prepare_job(self, r, row, ctx, wait):
        kind, m = row
        if kind == "F":
            if r > 0:
                wait(("F", m, r - 1))
            x = ctx["inputs"][m] if r == 0 else ctx["acts"][(m, r - 1)]
            return lambda: ctx["acts"].__setitem__(
                (m, r), self._fwd(r, m, x))
        if kind == "B":
            if r < self.n_stages - 1:
                wait(("B", m, r + 1))
            g = ctx["labels"][m] if r == self.n_stages - 1 \
                else ctx["cots"][(m, r + 1)]
            return lambda: ctx["cots"].__setitem__(
                (m, r), self._bwd_b(r, m, g))
        # W — own B already ran (sequential rank order)
        return lambda: self._bwd_w(r, m)


def per_rank_schedule(rank, n_stages, n_micro, schedule):
    """The per-rank event list (the rank-0 view lives on
    PipelineMicroScheduler). 1F1B: warmup of (n_stages-rank-1) forwards,
    steady 1F1B, backward cooldown (pipeline_parallel.py:565). ZB-H1:
    same warmup/steady; cooldown interleaves the deferred W jobs into the
    slots 1F1B leaves idle (pipeline_zero_bubble.py:62)."""
    if schedule in ZBV_SCHEDULES:
        raise ValueError(
            "ZB-V is chunked (2 virtual stages per rank): use "
            "build_zbv_rank_schedules, which returns (kind, micro, chunk) "
            "events per rank")
    warmup = min(n_stages - rank - 1, n_micro)
    evs = [("F", i) for i in range(warmup)]
    fwd, bwd, w = warmup, 0, 0
    zb = schedule in ZB_SCHEDULES
    while bwd < n_micro:
        if fwd < n_micro:
            evs.append(("F", fwd)); fwd += 1
            evs.append(("B", bwd)); bwd += 1
        else:
            evs.append(("B", bwd)); bwd += 1
            if zb and w < bwd:
                evs.append(("W", w)); w += 1
    while zb and w < n_micro:
        evs.append(("W", w)); w += 1
    return evs


class ThreadedZBVExecutor(_ThreadedPipelineBase):
    """ZB-V executed with true per-rank concurrency: each rank thread
    runs its (kind, micro, chunk) list from `build_zbv_rank_schedules`,
    with cross-rank dependency events keyed by VIRTUAL stage. This is
    the chunked sibling of ThreadedFleetExecutor (which deliberately
    refuses ZB-V names) — ZB-V is thereby executed AND measurable, not
    just enumerated. Parity: the reference's
    PipelineZeroBubbleVirtualPipelinePass schedules run on the
    interceptor runtime (`pipeline_zero_bubble.py:150`).

    Job signatures take the VIRTUAL stage s = zbv_stage_of(rank, chunk):
      fwd(s, m, x) -> activation
      bwd_b(s, m, g_or_label) -> cotangent  (split dx; fused when
                                             split_w=False)
      bwd_w(s, m) -> None                   (deferred dw, split_w only)
    """

    def __init__(self, n_ranks, n_micro, fwd, bwd_b, bwd_w=None,
                 split_w=True):
        if split_w and bwd_w is None:
            raise ValueError("split_w=True needs bwd_w (deferred weight "
                             "grads would silently be dropped)")
        self.n_ranks, self.n_micro = n_ranks, n_micro
        self.n_stages = 2 * n_ranks
        self._fwd, self._bwd_b, self._bwd_w = fwd, bwd_b, bwd_w
        self._split_w = split_w
        self.schedules, self.sim_makespan = build_zbv_rank_schedules(
            n_ranks, n_micro, split_w=split_w)
        self.timeline = {}
        self.errors = []

    def _n_workers(self):
        return self.n_ranks

    def _worker_rows(self, r):
        return self.schedules[r]

    def _event_key(self, r, row):
        kind, m, c = row
        return (kind, m, zbv_stage_of(r, c, self.n_ranks))

    def _schedule_name(self):
        return "ZB-V" if self._split_w else "V-1F1B"

    def _sim_makespan(self, durs):
        return build_zbv_rank_schedules(
            self.n_ranks, self.n_micro, t_f=durs["F"], t_b=durs["B"],
            t_w=durs.get("W", 0.0), split_w=self._split_w)[1]

    def _prepare_job(self, r, row, ctx, wait):
        kind, m, c = row
        s = zbv_stage_of(r, c, self.n_ranks)
        if kind == "F":
            if s > 0:
                wait(("F", m, s - 1))
            x = ctx["inputs"][m] if s == 0 else ctx["acts"][(m, s - 1)]
            return lambda: ctx["acts"].__setitem__(
                (m, s), self._fwd(s, m, x))
        if kind == "B":
            # own chunk's F may be on this rank but EARLIER events don't
            # imply it ran: the other chunk's jobs interleave
            wait(("F", m, s))
            if s < self.n_stages - 1:
                wait(("B", m, s + 1))
            g = ctx["labels"][m] if s == self.n_stages - 1 \
                else ctx["cots"][(m, s + 1)]
            return lambda: ctx["cots"].__setitem__(
                (m, s), self._bwd_b(s, m, g))
        wait(("B", m, s))
        return lambda: self._bwd_w(s, m)


def zbv_stage_of(rank, chunk, n_ranks):
    """ZB-V chunk placement (parity: reference
    `passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:343`
    VScheduleCreator / PipelineZeroBubbleVirtualPipelinePass:150):
    each rank holds two model chunks arranged in a V — chunk 0 descends
    ranks 0..p-1, chunk 1 ascends p-1..0, so the last rank owns the two
    middle virtual stages and cotangents turn around without a hop."""
    return rank if chunk == 0 else 2 * n_ranks - 1 - rank


def build_zbv_rank_schedules(n_ranks, n_micro, t_f=1.0, t_b=1.0, t_w=1.0,
                             split_w=True):
    """Greedy dependency-driven V-schedule creator. Builds per-rank
    ordered job lists for the 2-chunk V placement and returns
    (schedules, makespan).

    Jobs are (kind, micro, chunk) per rank; virtual-stage dependencies:
      F(m, s) after F(m, s-1);  B(m, s) after B(m, s+1) and F(m, s);
      W(m, s) after B(m, s)  (split_w=False folds W into B — the
      interleaved-1F1B baseline on the same V placement).
    Greedy priority per idle rank: B first (critical path), then F
    (earliest micro, lowest virtual stage), W only when nothing else is
    ready — deferred weight grads fill the bubbles, which is the whole
    zero-bubble idea. The discrete-event loop doubles as the makespan
    model (the same machinery `simulate_pipeline_makespan` uses)."""
    n_stages = 2 * n_ranks
    rank_of = {}
    for r in range(n_ranks):
        for c in (0, 1):
            rank_of[zbv_stage_of(r, c, n_ranks)] = (r, c)

    pending = {r: set() for r in range(n_ranks)}
    for s in range(n_stages):
        r, c = rank_of[s]
        for m in range(n_micro):
            pending[r].add(("F", m, c))
            pending[r].add(("B", m, c))
            if split_w:
                pending[r].add(("W", m, c))

    done = {}                      # (kind, m, s) -> end time
    rank_free = {r: 0.0 for r in range(n_ranks)}
    schedules = {r: [] for r in range(n_ranks)}
    dur = {"F": t_f, "B": t_b if split_w else t_b + t_w, "W": t_w}

    def ready_time(kind, m, c, r):
        s = zbv_stage_of(r, c, n_ranks)
        deps = []
        if kind == "F":
            if s > 0:
                deps.append(("F", m, s - 1))
        elif kind == "B":
            deps.append(("F", m, s))
            if s < n_stages - 1:
                deps.append(("B", m, s + 1))
        else:
            deps.append(("B", m, s))
        if any(d not in done for d in deps):
            return None
        return max((done[d] for d in deps), default=0.0)

    total = sum(len(v) for v in pending.values())
    while total:
        progressed = False
        # ranks in order of earliest availability keeps the event loop fair
        for r in sorted(pending, key=lambda q: rank_free[q]):
            if not pending[r]:
                continue
            best = None
            for kind, m, c in pending[r]:
                t0 = ready_time(kind, m, c, r)
                if t0 is None:
                    continue
                start = max(rank_free[r], t0)
                prio = {"B": 0, "F": 1, "W": 2}[kind]
                key = (start, prio, m, c)
                if best is None or key < best[0]:
                    best = (key, kind, m, c, start)
            if best is None:
                continue
            _, kind, m, c, start = best
            s = zbv_stage_of(r, c, n_ranks)
            done[(kind, m, s)] = start + dur[kind]
            rank_free[r] = start + dur[kind]
            schedules[r].append((kind, m, c))
            pending[r].discard((kind, m, c))
            total -= 1
            progressed = True
        if not progressed:
            raise RuntimeError("ZB-V schedule deadlock")
    return schedules, max(rank_free.values())


def zb_dispatch_tax_model(n_stages, n_micro, t_f, t_b, t_w,
                          overhead=0.0):
    """Explicit win/lose model for ZB-H1 vs 1F1B at a given
    (pp, micro, t_f/t_b/t_w) point — VERDICT r5 #6: the measured
    BENCH_PIPELINE rows showed ZB sometimes LOSING, and the reason is
    structural, not noise, so the selector needs a model, not a slogan.

    Two opposing terms:

    * **bubble saved** — the deferred W jobs fill 1F1B's cooldown
      bubbles. Quantified by the dependency simulator at overhead 0:
      `sim_1f1b - sim_zb` (can be NEGATIVE: with measured durations
      where t_w > t_b, parking W after the B chain can LENGTHEN the
      critical path — that is exactly what the measured (2,8)/(4,4)
      rows show).
    * **dispatch tax** — ZB dispatches ~`n_micro` extra W jobs per
      rank; each job dispatch costs `overhead` seconds (host dispatch
      + launch latency; BENCH_PIPELINE's 1-core wall columns put the
      two-dispatch split at ~10% of a fused backward on this host).
      Modeled exactly, not as a scalar correction: every job's duration
      is inflated by `overhead` and the same dependency simulation is
      re-run — 3 dispatches per micro per rank for ZB (F, B, W)
      against 2 for 1F1B (F, fused B+W).

    Returns a dict: predicted makespans (with the tax), the two terms,
    extra_w_dispatches, and `verdict` ("ZB-H1" when it wins, else
    "1F1B"). `simulate_pipeline_makespan` is the single source of the
    dependency model — this function only composes it.
    """
    t_f, t_b, t_w = float(t_f), float(t_b), float(t_w)
    h = float(overhead)
    base_1f1b = simulate_pipeline_makespan(n_stages, n_micro, "1F1B",
                                           t_f=t_f, t_b=t_b, t_w=t_w)
    base_zb = simulate_pipeline_makespan(n_stages, n_micro, "ZB-H1",
                                         t_f=t_f, t_b=t_b, t_w=t_w)
    # overhead folds into each dispatched job: 1F1B's backward is ONE
    # dispatch (fused b+w), so its tax rides the fused duration via t_w
    pred_1f1b = simulate_pipeline_makespan(
        n_stages, n_micro, "1F1B", t_f=t_f + h, t_b=t_b, t_w=t_w + h)
    pred_zb = simulate_pipeline_makespan(
        n_stages, n_micro, "ZB-H1", t_f=t_f + h, t_b=t_b + h,
        t_w=t_w + h)
    return {
        "n_stages": int(n_stages), "n_micro": int(n_micro),
        "t_f": t_f, "t_b": t_b, "t_w": t_w, "overhead": h,
        "bubble_saved": base_1f1b - base_zb,
        "extra_w_dispatches": int(n_stages) * int(n_micro),
        "dispatch_tax": (pred_zb - base_zb) - (pred_1f1b - base_1f1b),
        "predicted_1f1b": pred_1f1b,
        "predicted_zb": pred_zb,
        "verdict": "ZB-H1" if pred_zb < pred_1f1b else "1F1B",
    }


def choose_pipeline_schedule(n_stages, n_micro, t_f, t_b, t_w,
                             overhead=0.0):
    """Schedule selector gated on the dispatch-tax model: returns
    "ZB-H1" only when the modeled bubble saving survives the modeled
    per-dispatch overhead at this (pp, micro, durations) point —
    otherwise 1F1B (whose fused backward pays one dispatch, not two).
    Feed measured durations (`ThreadedFleetExecutor.measured_durations`
    or BENCH_PIPELINE rows), not unit guesses: the unit-time model
    over-predicts ZB wins on every measured row (BENCH_PIPELINE.md)."""
    return zb_dispatch_tax_model(n_stages, n_micro, t_f, t_b, t_w,
                                 overhead=overhead)["verdict"]


def simulate_pipeline_makespan(n_stages, n_micro, schedule,
                               t_f=1.0, t_b=1.0, t_w=1.0):
    """Dependency-respecting makespan of the per-rank schedules under a
    unit-time stage model (the measurement VERDICT r2 weak #5 demanded).

    Durations: F = t_f; ZB's split backward = t_b (dx) + a separate t_w
    (dw) job; 1F1B's fused backward = t_b + t_w on the critical path.
    Dependencies: F(m,r) needs F(m,r-1); B(m,r) needs B(m,r+1) (or its
    own F for the last stage) and F(m,r); W(m,r) needs B(m,r).
    """
    if schedule in ZBV_SCHEDULES:
        # V placement has its own creator+model; its discrete-event loop
        # returns the makespan directly
        return build_zbv_rank_schedules(n_stages, n_micro, t_f=t_f,
                                        t_b=t_b, t_w=t_w)[1]
    zb = schedule in ZB_SCHEDULES
    queues = {r: list(per_rank_schedule(r, n_stages, n_micro, schedule))
              for r in range(n_stages)}
    end: Dict[tuple, float] = {}
    rank_time = {r: 0.0 for r in range(n_stages)}
    dur = {"F": t_f, "B": t_b if zb else t_b + t_w, "W": t_w}

    def ready(kind, m, r):
        deps = []
        if kind == "F":
            if r > 0:
                deps.append(("F", m, r - 1))
        elif kind == "B":
            deps.append(("F", m, r))
            if r < n_stages - 1:
                deps.append(("B", m, r + 1))
        else:
            deps.append(("B", m, r))
        if any(d not in end for d in deps):
            return None
        return max((end[d] for d in deps), default=0.0)

    progress = True
    while progress and any(queues.values()):
        progress = False
        for r in range(n_stages):
            while queues[r]:
                kind, m = queues[r][0]
                t0 = ready(kind, m, r)
                if t0 is None:
                    break
                start = max(rank_time[r], t0)
                end[(kind, m, r)] = start + dur[kind]
                rank_time[r] = start + dur[kind]
                queues[r].pop(0)
                progress = True
    if any(queues.values()):
        raise RuntimeError(f"schedule deadlock: {queues}")
    return max(rank_time.values())
