"""paddle.distributed.rpc parity surface (not applicable on TPU SPMD; kept
as explicit unsupported stubs, see SURVEY.md A.7)."""
__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown"]


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    raise NotImplementedError("rpc is out of the TPU north-star path")


rpc_sync = rpc_async = shutdown = init_rpc
