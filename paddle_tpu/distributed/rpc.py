"""paddle.distributed.rpc — tensor/object RPC between workers.

Parity: reference `python/paddle/distributed/rpc/` over the brpc C++
layer (`paddle/fluid/distributed/rpc/`): init_rpc / rpc_sync / rpc_async /
get_worker_info / shutdown.

TPU-native: the transport is the native TCPStore (the same rendezvous KV
the launcher uses) — each worker runs a serve thread that blocks on its
sequential mailbox keys, executes the pickled callable, and posts the
pickled result. Functions are pickled by reference (must be importable on
the callee), mirroring the reference's serialization contract. Arrays in
args/results travel as numpy (host) buffers — RPC is a control-plane
tool; bulk tensor movement belongs to the collectives.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Dict, Optional

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "get_worker_info",
           "get_current_worker_info",
           "get_all_worker_infos", "shutdown", "WorkerInfo"]

_state = {"name": None, "store": None, "serve": None, "stop": None,
          "world_size": 1}



class WorkerInfo:
    """Parity: rpc.get_worker_info result (name, rank, ip, port)."""

    def __init__(self, name, rank, ip=None, port=None):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


class _Future:
    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def _set(self, value=None, exc=None):
        self._value, self._exc = value, exc
        self._event.set()

    def wait(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("rpc result timed out")
        if self._exc is not None:
            raise self._exc
        return self._value

    def done(self):
        return self._event.is_set()


def _gen_stopped(store, name, gen):
    raw = store.get(f"rpc/stopgen/{name}", wait=False)
    try:
        return raw is not None and int(raw.decode()) >= gen
    except ValueError:
        return False


def _serve_loop(name, store, stop, start_seq, gen):
    # Resume from the served counter: a re-init after shutdown (elastic
    # restart) must not replay already-executed mailbox entries. Shutdown
    # is an out-of-band generation key, NOT an in-band marker — a marker
    # left unconsumed by a busy dying loop would instantly kill the next
    # generation's serve loop.
    seq = start_seq
    while not stop.is_set():
        key = f"rpc/q/{name}/{seq}"
        raw = store.get(key, wait=False)
        if raw is None:
            # shutdown honored only once the mailbox is drained (pending
            # callers get answers, not 60s timeouts), and the gen key is
            # polled on the idle path only (half the store traffic)
            if _gen_stopped(store, name, gen):
                return
            time.sleep(0.005)
            continue
        seq += 1
        store.add(f"rpc/served/{name}", 1)
        try:
            fn, args, kwargs = pickle.loads(raw)
            result = fn(*args, **kwargs)
            payload = pickle.dumps(("ok", result))
        except BaseException as e:  # marshalled back to the caller
            try:
                payload = pickle.dumps(("err", e))
            except Exception:
                payload = pickle.dumps(
                    ("err", RuntimeError(f"unpicklable {type(e).__name__}: "
                                         f"{e}")))
        try:
            store.set(key + "/ret", payload)
        except Exception:
            # unpicklable RESULT: report instead of killing the serve thread
            store.set(key + "/ret", pickle.dumps(
                ("err", RuntimeError("rpc result was not picklable"))))


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's serve loop and register its name.
    Parity: rpc/__init__.py init_rpc."""
    from .env import create_store
    if _state["serve"] is not None:
        return
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    store = create_store(master_endpoint, rank=rank)
    store.set(f"rpc/worker/{name}", pickle.dumps(WorkerInfo(name, rank)))
    store.add("rpc/nworkers", 1)
    stop = threading.Event()
    start_seq = store.add(f"rpc/served/{name}", 0)
    gen = store.add(f"rpc/gen/{name}", 1)
    t = threading.Thread(target=_serve_loop,
                         args=(name, store, stop, start_seq, gen),
                         daemon=True)
    t.start()
    _state.update(name=name, store=store, serve=t, stop=stop,
                  world_size=world_size, gen=gen)


def get_worker_info(name):
    raw = _state["store"].get(f"rpc/worker/{name}", wait=True)
    return pickle.loads(raw)


def get_current_worker_info():
    """Parity: rpc.get_current_worker_info — this process's WorkerInfo."""
    if _state["name"] is None:
        raise RuntimeError("call init_rpc first")
    return get_worker_info(_state["name"])


def get_all_worker_infos():
    # names are announced under rpc/worker/<name>; the store has no scan,
    # so infos are collected lazily by name — callers usually know names
    raise NotImplementedError(
        "enumerate workers by name with get_worker_info(name)")


def rpc_async(to, fn, args=None, kwargs=None, timeout=60.0):
    """Post (fn, args) to `to`'s mailbox; returns a Future.
    Parity: rpc/__init__.py rpc_async."""
    store = _state["store"]
    if store is None:
        raise RuntimeError("call init_rpc first")
    seq = store.add(f"rpc/ctr/{to}", 1) - 1
    key = f"rpc/q/{to}/{seq}"
    store.set(key, pickle.dumps((fn, tuple(args or ()), dict(kwargs or {}))))
    fut = _Future()

    def _poll():
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            raw = store.get(key + "/ret", wait=False)
            if raw is not None:
                status, value = pickle.loads(raw)
                if status == "ok":
                    fut._set(value=value)
                else:
                    fut._set(exc=value)
                return
            time.sleep(0.005)
        fut._set(exc=TimeoutError(f"rpc to {to!r} timed out"))

    threading.Thread(target=_poll, daemon=True).start()
    return fut


def rpc_sync(to, fn, args=None, kwargs=None, timeout=60.0):
    """Parity: rpc/__init__.py rpc_sync."""
    return rpc_async(to, fn, args, kwargs, timeout).wait(timeout + 1.0)


def shutdown():
    """Stop the local serve loop (parity: rpc.shutdown) via the
    out-of-band generation key; a later init_rpc bumps the generation and
    serves on, unaffected by prior shutdowns."""
    name, store, stop = _state["name"], _state["store"], _state["stop"]
    if store is None or _state["serve"] is None:
        return
    store.set(f"rpc/stopgen/{name}", str(_state["gen"]).encode())
    _state["serve"].join(timeout=5)
    stop.set()  # fallback if the loop is stuck inside a long RPC
    _state.update(name=None, serve=None, stop=None)
