"""ZeRO sharding (stages 1/2/3) — parameter/gradient/optimizer-state
partitioning over the 'sharding' mesh axis.

Parity: reference dygraph sharding —
`fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:53`
(stage 1), `:580` (V2 grad-view stage 2), and the group_sharded API
(`python/paddle/distributed/sharding/group_sharded.py:50` ->
GroupShardedOptimizerStage2/GroupShardedStage2/GroupShardedStage3).

TPU-native collapse: ZeRO is a *placement policy*, not a runtime protocol.
  stage 1  — optimizer accumulators sharded over the axis;
  stage 2  — + gradients reduced into sharded form (XLA reduce_scatter when
             the train step is compiled: grads inherit the accumulator
             sharding via the update expression);
  stage 3  — + parameters stored sharded; XLA all_gathers them on use
             (the weights-gather the reference does with forward hooks in
             group_sharded_stage3.py:901).
The policy places each tensor's first divisible axis on 'sharding'; XLA
GSPMD then emits the same collectives the reference's hand-written stages
issue (reduce_scatter for grads, all_gather for gathered params).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "ShardingStage1", "ShardingStage2", "ShardingStage3",
           "shard_spec_for", "DygraphShardingOptimizer"]

SHARDING_AXIS = "sharding"


def _mesh():
    from .fleet import fleet as fleet_mod
    hcg = fleet_mod._hcg
    return hcg.mesh if hcg is not None else None


def shard_spec_for(shape, axis_size, existing_spec=None):
    """Choose a dim to shard over 'sharding' (first divisible, not already
    sharded); None if nothing fits or the tensor is already placed on the
    sharding axis."""
    entries = list(existing_spec) if existing_spec is not None else [None] * len(shape)
    while len(entries) < len(shape):
        entries.append(None)
    for e in entries:
        taken = e if isinstance(e, (tuple, list)) else (e,)
        if SHARDING_AXIS in taken:
            return None  # already sharded over the axis
    for d, s in enumerate(shape):
        if entries[d] is None and s % axis_size == 0 and s >= axis_size:
            entries[d] = SHARDING_AXIS
            return P(*entries)
    return None


class _ShardingStageBase:
    """Placement policy, also usable as dist.shard_optimizer's shard_fn
    (parity: ShardingStage1/2/3 in auto_parallel/api.py:1306-1504)."""

    stage = 0

    def __init__(self, mesh=None, sharding_mesh_dim=SHARDING_AXIS):
        self._mesh_obj = mesh
        self._axis = sharding_mesh_dim

    def _jax_mesh(self):
        m = self._mesh_obj
        if m is None:
            return _mesh()
        return m.jax_mesh if hasattr(m, "jax_mesh") else m

    def _place(self, arr):
        mesh = self._jax_mesh()
        if mesh is None or self._axis not in mesh.shape:
            return arr
        size = mesh.shape[self._axis]
        if size <= 1:
            return arr
        cur = getattr(arr, "sharding", None)
        cur_spec = getattr(cur, "spec", None)
        spec = shard_spec_for(arr.shape, size, cur_spec)
        if spec is None:
            # nothing shardable left — includes "already placed", so the
            # per-step path is a no-op once state carries its sharding
            return arr
        return jax.device_put(arr, NamedSharding(mesh, spec))

    # shard_fn protocol: (acc_name, param, acc_tensor) -> new acc tensor
    def __call__(self, name, param, acc):
        return Tensor(self._place(acc._data))

    def apply_params(self, parameters):
        return parameters

    def apply_gradients(self, parameters):
        for p in parameters:
            if p._grad_buffer is not None:
                p._grad_buffer = self._place(p._grad_buffer)


class ShardingStage1(_ShardingStageBase):
    stage = 1


class ShardingStage2(ShardingStage1):
    stage = 2


class ShardingStage3(ShardingStage2):
    stage = 3

    def apply_params(self, parameters):
        for p in parameters:
            p._data = self._place(p._data)
        return parameters


class DygraphShardingOptimizer:
    """Stage-aware optimizer wrapper (parity:
    dygraph_sharding_optimizer.py:53). Shards accumulators (and params for
    stage 3) after each step; reduce_gradients applies the grad placement."""

    def __init__(self, optimizer, hcg=None, stage=1):
        self._inner = optimizer
        policy_cls = {1: ShardingStage1, 2: ShardingStage2,
                      3: ShardingStage3}[stage]
        mesh = hcg.mesh if hcg is not None else None
        self._policy = policy_cls(mesh)
        self.stage = stage
        if stage >= 3:
            self._policy.apply_params(optimizer._parameter_list)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def reduce_gradients(self, parameter_list=None, hcg=None):
        self._policy.apply_gradients(parameter_list or
                                     self._inner._parameter_list)

    def step(self):
        if self.stage >= 2:
            self.reduce_gradients()
        params = self._inner._parameter_list
        self._inner.step()
        for name, slot in self._inner._accumulators.items():
            for idx, arr in slot.items():
                p = params[idx]
                new = self._policy(name, p, Tensor(arr))
                slot[idx] = new._data
        if self.stage >= 3:
            self._policy.apply_params(params)
        else:
            # stages 1/2 keep parameters replicated: the eager update math
            # propagates the accumulators' sharded layout onto the updated
            # params, so re-replicate over the mesh (the reference's
            # post-update broadcast of owned shards). Mesh-replicated, not
            # single-device: committing to one device would clash with the
            # mesh-resident optimizer state in later steps.
            mesh = self._policy._jax_mesh()
            if mesh is not None:
                for p in params:
                    spec = getattr(getattr(p._data, "sharding", None),
                                   "spec", None)
                    if spec is None:
                        continue
                    flat = [e for ent in spec if ent is not None
                            for e in (ent if isinstance(ent, tuple) else
                                      (ent,))]
                    if SHARDING_AXIS in flat:
                        p._data = jax.device_put(
                            p._data,
                            NamedSharding(mesh, P(*([None] * p._data.ndim))))

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Parity: paddle.distributed.sharding.group_sharded_parallel
    (group_sharded.py:50). level: 'os' (stage1) | 'os_g' (stage2) |
    'p_g_os' (stage3)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    from .fleet import fleet as fleet_mod
    hcg = fleet_mod._hcg
    opt = DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    return model, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Parity: group_sharded.py:199 — gather full params and save."""
    import os
    from ..framework.io import save
    sd = {}
    for k, t in model.state_dict().items():
        arr = t._data
        if hasattr(arr, "sharding") and hasattr(arr, "is_fully_replicated") \
                and not arr.is_fully_replicated:
            arr = jax.device_put(
                arr, NamedSharding(arr.sharding.mesh, P(*([None] * arr.ndim))))
        sd[k] = Tensor(arr)
    path = output if output.endswith(".pdparams") else \
        os.path.join(output, "model.pdparams")
    save(sd, path)
    if optimizer is not None:
        save(optimizer.state_dict(),
             path.replace(".pdparams", ".pdopt"))
