"""ZeRO sharding stages (placeholder — implemented in fleet.sharding next)."""
from __future__ import annotations

__all__ = ["group_sharded_parallel"]


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False):
    raise NotImplementedError("implemented in the next milestone")
