"""Context parallelism — long-sequence attention over the `sep` mesh axis.

Capability-parity-plus (SURVEY.md §5): the reference's long-context story is
Megatron-SP (`fleet/utils/sequence_parallel_utils.py`) plus the `sep`
topology axis (`fleet/base/topology.py:70-90`); ring attention lives outside
its core. Here both ring (ppermute K/V rotation) and Ulysses (all_to_all
head/seq swap) are first-class, built on the Pallas flash kernel.

Two entry levels:
  * `ring_attention` / `ulysses_attention` (re-exported from
    paddle_tpu.kernels.ring_attention) — call INSIDE shard_map on local
    shards;
  * `context_parallel_attention` — takes global jax.Arrays sequence-sharded
    over `sep` on an ambient mesh and wraps the shard_map for you.
"""
from __future__ import annotations

import jax
try:
    from jax import shard_map
except ImportError:  # older jax: experimental
    from ..jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from ..kernels.ring_attention import ring_flash_attention, ulysses_attention

__all__ = ["ring_attention", "ulysses_attention",
           "context_parallel_attention"]

ring_attention = ring_flash_attention


def context_parallel_attention(q, k, v, mesh=None, axis_name="sep",
                               causal=True, mode="ring", sm_scale=None):
    """Attention over (B, S, H, D) arrays whose sequence dim is sharded on
    `axis_name`. mode: "ring" (ppermute ring flash) or "ulysses"
    (all_to_all head swap). Returns an array with the same sharding.
    """
    if mesh is None:
        sh = getattr(q, "sharding", None)
        mesh = getattr(sh, "mesh", None)
    if mesh is None:
        # under jit tracing: the aval carries the AbstractMesh
        aval = getattr(q, "aval", None)
        sh = getattr(aval, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "empty", False):
            mesh = None
    if mesh is None:
        raise ValueError("inputs carry no mesh; pass mesh= explicitly")
    if mode == "ring":
        inner = lambda a, b, c: ring_flash_attention(
            a, b, c, axis_name, causal=causal, sm_scale=sm_scale)
    elif mode == "ulysses":
        inner = lambda a, b, c: ulysses_attention(
            a, b, c, axis_name, causal=causal, sm_scale=sm_scale)
    else:
        raise ValueError(f"unknown context-parallel mode {mode!r}")
    spec = P(None, axis_name)
    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
