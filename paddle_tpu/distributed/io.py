"""paddle.distributed.io — persistable save/load for static programs.

Parity: reference `python/paddle/distributed/io.py`
(save_persistables / load_persistables / is_persistable over a static
Program + Executor). Here persistables are the parameters and
global-scope vars of the traced static Program; artifacts are one
pickled numpy dict per directory (the distributed sharded path is
distributed.checkpoint).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var) -> bool:
    """Parameters and named global-scope vars persist; temporaries don't."""
    from ..core.tensor import Tensor
    if not isinstance(var, Tensor):
        return False
    return bool(getattr(var, "_is_param", False)) or bool(var.name)


def _collect(program=None):
    from ..static import default_main_program, global_scope
    prog = program or default_main_program()
    out = {}
    for name, var in global_scope().vars.items():
        if is_persistable(var):
            out[name] = np.asarray(var._data)
    for p in getattr(prog, "parameters", lambda: [])():
        if p.name:
            out[p.name] = np.asarray(p._data)
    return out


def save_persistables(executor=None, dirname="./", main_program=None,
                      filename=None):
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__persistables__")
    with open(path, "wb") as f:
        pickle.dump(_collect(main_program), f)
    return path


def load_persistables(executor=None, dirname="./", main_program=None,
                      filename=None):
    import jax.numpy as jnp
    from ..static import global_scope
    path = os.path.join(dirname, filename or "__persistables__")
    with open(path, "rb") as f:
        state = pickle.load(f)
    scope = global_scope()
    for name, arr in state.items():
        if name in scope.vars:
            scope.vars[name]._data = jnp.asarray(arr)
    return state
