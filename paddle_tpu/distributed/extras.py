"""Remaining paddle.distributed top-level surface.

Parity targets (reference python/paddle/distributed/__init__.py names
that had no home yet): communication conveniences (gather, wait,
isend/irecv, scatter_object_list, alltoall aliases, gloo_*), the
megatron `split` op, spawn, ParallelMode/ReduceType enums, auto-parallel
conveniences (dtensor_from_fn, shard_dataloader, shard_scaler,
Strategy), and the PS-era dataset/entry configs (config objects are
real; server-touching methods raise — this build excludes the parameter
server per SURVEY A.7, and a silent no-op would be worse than an error).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from .collective import (ReduceOp, Task, _axis_in_trace, _default_group,
                         _resolve_axis, all_gather, all_to_all,
                         all_to_all_single, barrier, recv, send)

__all__ = [
    "gather", "wait", "isend", "irecv", "scatter_object_list", "alltoall",
    "alltoall_single", "gloo_init_parallel_env", "gloo_barrier",
    "gloo_release", "split", "spawn", "ParallelMode", "ReduceType",
    "dtensor_from_fn", "shard_dataloader", "ShardDataloader",
    "shard_scaler", "Strategy", "QueueDataset", "InMemoryDataset",
    "CountFilterEntry", "ShowClickEntry", "ProbabilityEntry",
]

alltoall = all_to_all
alltoall_single = all_to_all_single


class ParallelMode:
    """Parity: paddle.distributed.ParallelMode (parallel.py)."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    """Parity: dist.ReduceType (dtensor partial reduce kinds)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


def wait(tensor, group=None, use_calc_stream=True):
    """Parity: dist.wait — block until the tensor's producing work is
    visible. On TPU every array is an async future: block_until_ready."""
    d = getattr(tensor, "_data", tensor)
    if hasattr(d, "block_until_ready"):
        try:
            d.block_until_ready()
        except Exception:
            pass
    return None


def isend(tensor, dst=0, group=None):
    """Async point-to-point (parity: dist.isend). Same contract as send:
    out-of-schedule p2p is not supported on the TPU build."""
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Parity: dist.gather — collect shards to `dst`. SPMD note: inside a
    mesh trace every rank computes the full gather (XLA all_gather); the
    dst-only visibility of the reference is a host-side convention."""
    if gather_list is None:
        gather_list = []
    return all_gather(gather_list, tensor, group=group, sync_op=sync_op)


def scatter_object_list(out_object_list, in_object_list, src=0, group=None):
    """Parity: dist.scatter_object_list (single-process world: rank 0's
    slot)."""
    g = group or _default_group()
    rank = max(g.rank, 0)
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[rank % len(in_object_list)])
    return out_object_list


def gloo_init_parallel_env(rank_id=0, rank_num=1, server_endpoint=None):
    """Parity: dist.gloo_init_parallel_env — CPU-side barrier bootstrap;
    maps onto the standard store-based init."""
    from .env import init_parallel_env
    init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    return None


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Parity: dist.split (the megatron helper creating a column/row
    parallel linear or a vocab-parallel embedding in one call,
    reference collective.py split). Uses the mpu layers; the created
    parameters live on the returned layer (`split.last_layer`) for
    callers that train through them."""
    from .fleet import mpu
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = mpu.ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, gather_output=gather_out)
        elif axis == 0:
            layer = mpu.RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, input_is_parallel=False)
        else:
            raise ValueError("split(linear) axis must be 0 or 1")
    elif operation == "embedding":
        n, d = size
        layer = mpu.VocabParallelEmbedding(n, d, weight_attr=weight_attr)
    else:
        raise ValueError(f"split: unknown operation {operation!r}")
    split.last_layer = layer
    return layer(x)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: dist.spawn — launch `func` in nprocs processes with the
    trainer env prepared (PADDLE_MASTER / TRAINER_ID / TRAINERS_NUM), the
    same env contract as distributed.launch. Returns the context (with
    .processes) when join=False."""
    import multiprocessing as mp
    import os
    import socket

    if nprocs <= 0:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    master = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_MASTER": master, "PADDLE_TRAINERS_NUM": str(nprocs),
               "PADDLE_TRAINER_ID": str(rank)}
        p = ctx.Process(target=_spawn_entry,
                        args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)

    class _Ctx:
        processes = procs

        def join(self, timeout=None):
            for p in procs:
                p.join(timeout)
            bad = [p.exitcode for p in procs if p.exitcode]
            if bad:
                raise RuntimeError(f"spawn: child exit codes {bad}")

    c = _Ctx()
    if join:
        c.join()
    return c


def _spawn_entry(func, args, env):
    import os
    os.environ.update(env)
    func(*args)


# ------------------------------------------------- auto-parallel extras
def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """Parity: dist.dtensor_from_fn (api.py) — build then place."""
    from .auto_parallel.api import shard_tensor
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


class ShardDataloader:
    """Iterates a DataLoader placing each batch on the mesh (batch dim
    sharded over the mesh's first axis, or `shard_dims`). Parity:
    dist.shard_dataloader / ShardDataloader (auto_parallel/api.py)."""

    def __init__(self, dataloader, meshes, shard_dims=None,
                 is_dataset_splitted=False):
        self._dl = dataloader
        self._mesh = meshes[0] if isinstance(meshes, (list, tuple)) \
            else meshes
        self._dims = shard_dims

    def __len__(self):
        return len(self._dl)

    def _place(self, item):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        jmesh = getattr(self._mesh, "jax_mesh", self._mesh)
        dim = self._dims or list(jmesh.shape.keys())[0]
        def put(t):
            if isinstance(t, Tensor):
                return Tensor(jax.device_put(
                    t._data, NamedSharding(jmesh, P(dim))),
                    stop_gradient=t.stop_gradient)
            return t
        if isinstance(item, (list, tuple)):
            return type(item)(put(t) for t in item)
        return put(item)

    def __iter__(self):
        for item in self._dl:
            yield self._place(item)


def shard_dataloader(dataloader, meshes, shard_dims=None,
                     is_dataset_splitted=False):
    return ShardDataloader(dataloader, meshes, shard_dims,
                           is_dataset_splitted)


def shard_scaler(scaler):
    """Parity: dist.shard_scaler — the GradScaler already operates on
    sharded jax arrays (its jnp reductions run the mesh collectives), so
    the wrap is the identity; kept for API compatibility."""
    return scaler


class _Flags:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class Strategy:
    """Parity: dist.Strategy (auto_parallel/strategy.py) — the config
    object dist.to_static accepts: sharding/amp/pipeline/fused_passes
    sub-configs."""

    def __init__(self, config=None):
        cfg = config or {}

        def flags(key, **defaults):
            defaults.update(cfg.get(key, {}))
            return _Flags(**defaults)

        self.sharding = flags("sharding", enable=False, stage=1, degree=8)
        self.amp = flags("amp", enable=False, dtype="float16", level="O1")
        self.pipeline = flags("pipeline", enable=False,
                              schedule_mode="1F1B", micro_batch_size=1,
                              accumulate_steps=1)
        self.fused_passes = flags("fused_passes", enable=False,
                                  fused_passes_list=[])
        self.gradient_merge = flags("gradient_merge", enable=False,
                                    k_steps=1, avg=True)


# --------------------------------------------------- PS-era data configs
class _EntryBase:
    def __init__(self, *a):
        self._args = a

    def _to_attr(self):
        return f"{type(self).__name__.lower()}:{':'.join(map(str, self._args))}"


class CountFilterEntry(_EntryBase):
    """Parity: dist.CountFilterEntry — sparse-table admission by count."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        super().__init__(count_filter)


class ShowClickEntry(_EntryBase):
    """Parity: dist.ShowClickEntry — show/click slot names."""

    def __init__(self, show_name, click_name):
        super().__init__(show_name, click_name)


class ProbabilityEntry(_EntryBase):
    """Parity: dist.ProbabilityEntry — probabilistic admission."""

    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        super().__init__(probability)


class _PSDataset:
    """Config surface of the PS dataset pipeline. The parameter-server
    runtime is excluded from the TPU build (SURVEY A.7): configuration
    calls work, pipeline execution raises instead of silently no-opping."""

    def __init__(self):
        self._filelist: List[str] = []
        self._pipe_command = "cat"
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []

    def init(self, **kwargs):
        self._batch_size = kwargs.get("batch_size", self._batch_size)
        self._thread_num = kwargs.get("thread_num", self._thread_num)
        self._pipe_command = kwargs.get("pipe_command", self._pipe_command)
        self._use_var = kwargs.get("use_var", self._use_var)

    update_settings = init

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _raise(self, what):
        raise NotImplementedError(
            f"{type(self).__name__}.{what}: the parameter-server data "
            "pipeline is not part of the TPU build (SURVEY A.7); use "
            "paddle.io.DataLoader, which feeds the same training APIs")

    def load_into_memory(self):
        self._raise("load_into_memory")

    def preload_into_memory(self, thread_num=None):
        self._raise("preload_into_memory")

    def release_memory(self):
        return None


class QueueDataset(_PSDataset):
    """Parity: dist.QueueDataset (streaming PS dataset)."""


class InMemoryDataset(_PSDataset):
    """Parity: dist.InMemoryDataset (shuffleable PS dataset)."""

    def local_shuffle(self):
        self._raise("local_shuffle")

    def global_shuffle(self, fleet=None, thread_num=12):
        self._raise("global_shuffle")
