"""DataParallel wrapper.

Parity: reference `paddle.DataParallel` (`python/paddle/distributed/
parallel.py:219`) + the C++ EagerReducer. TPU-native: gradient sync is not a
bucketed NCCL allreduce — when the train step is compiled over a mesh with
the batch axis sharded ('data'), XLA inserts the gradient psum automatically
(GSPMD). This wrapper therefore (a) marks the model's intended data-parallel
axis, (b) in in-trace contexts performs grad averaging over that axis
explicitly for parity with no-pjit flows.
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .collective import _axis_in_trace, all_reduce, ReduceOp
from .env import get_world_size, init_parallel_env  # noqa: F401

__all__ = ["DataParallel", "init_parallel_env"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Average gradients over the data axis (in-trace) — the analog of
        the reference's fused allreduce in EagerReducer."""
        axis = self._group.axis_name if self._group else "data"
        if not _axis_in_trace(axis):
            return
        for p in self._layers.parameters():
            if p._grad_buffer is not None:
                p._grad_buffer = jax.lax.pmean(p._grad_buffer, axis)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
