"""Pipeline parallelism (placeholder — ppermute 1F1B next)."""
__all__ = []
