"""In-graph pipeline parallelism over the 'pipe' mesh axis.

Parity: reference pipeline runtime — micro-batch schedules
(`fleet/meta_parallel/pipeline_parallel.py:565` 1F1B, `:1161` interleave /
virtual pipeline, static passes `passes/pipeline_scheduler_pass/`) and the
P2P layer (`pp_utils/p2p_communication.py` batched isend/irecv).

TPU-native: there is no host-driven micro-step loop with NCCL p2p. The
whole schedule is one compiled XLA program: stage weights are stacked on a
leading dim sharded over 'pipe'; a lax.scan over ticks moves activations
between neighbor stages with lax.ppermute (ICI neighbor exchange — the
send_v2/recv_v2 analog); jax AD differentiates the scan, so the backward
pipeline (reverse ppermute chain) is derived, not hand-scheduled. Memory is
controlled with jax.checkpoint per stage (the reference needs 1F1B for
this; remat-in-scan achieves the same peak-activation bound, with the
schedule left to the XLA scheduler).

The shard_map is *partial-manual*: only the pipe axis is manual
(`axis_names={'pipe'}`); every other hybrid axis (data/model/sep/sharding)
stays automatic, so GSPMD tensor-parallel sharding constraints inside a
stage body keep working — pp composes with tp/dp/sp in one program.

Interleaved (virtual-pipeline) schedule: with ``n_virtual > 1`` each device
owns ``n_virtual`` non-adjacent layer chunks (chunk c lives at device
``c % n_stages``, round ``c // n_stages``), and micro-batches circulate the
device ring ``n_virtual`` times — the circular schedule of the reference's
`PipelineParallelWithInterleave` (`pipeline_parallel.py:1161`). Micro-batches
are processed in groups of ``n_stages``; per group the bubble shrinks from
``(n_stages-1)`` full-stage slots to ``(n_stages-1)`` chunk slots (a
``1/n_virtual`` reduction, the interleave payoff).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax: experimental
    from ..jax_compat import shard_map

__all__ = ["pipeline_forward", "stack_stage_params", "PipelineMicroScheduler"]

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage_params, n_virtual: int = 1):
    """List (len n_stages*n_virtual, chunk-major: chunk c = v*n_stages + d)
    of identical-structure pytrees -> stacked pytree. Leaves gain a leading
    (n_stages, ...) dim for n_virtual == 1, or (n_virtual, n_stages, ...)
    dims otherwise; the stage dim is sharded over 'pipe'."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *per_stage_params)
    if n_virtual == 1:
        return stacked
    n_chunks = len(per_stage_params)
    n_stages = n_chunks // n_virtual
    return jax.tree_util.tree_map(
        lambda a: a.reshape(n_virtual, n_stages, *a.shape[1:]), stacked)


def pipeline_forward(stage_params, micro_inputs, stage_fn: Callable, mesh,
                     axis: str = PIPE_AXIS, remat: bool = True,
                     extras=(), n_virtual: int = 1):
    """Run `stage_fn(params, x, *extras) -> y` as a pipeline over `axis`.

    stage_params: pytree; leaves (n_stages, ...) — or, when n_virtual > 1,
        (n_virtual, n_stages, ...) — sharded over `axis` on the stage dim.
    micro_inputs: (n_micro, *mb_shape) — replicated over `axis` (stage 0
        consumes them; ppermute forwards activations down the chain).
    extras: arrays passed unchanged to every stage invocation (e.g. rope
        tables), replicated over `axis`.
    Returns (n_micro, *mb_shape) outputs of the final chunk, replicated
    over `axis` (zero-padded contributions psum-gathered).

    Differentiable end-to-end: jax.grad of a loss on the returned outputs
    yields the reverse pipeline automatically.
    """
    if n_virtual > 1:
        return _pipeline_circular(stage_params, micro_inputs, stage_fn, mesh,
                                  axis, remat, extras, n_virtual)
    n_stages = mesh.shape[axis]
    n_micro = micro_inputs.shape[0]
    total_ticks = n_micro + n_stages - 1

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    extra_specs = tuple(P() for _ in extras)

    def per_device(params, xs, *ex):
        # params leaves: (1, ...) — this device's stage; squeeze lead dim
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage_id = jax.lax.axis_index(axis)

        def fn_(p, x):
            return stage_fn(p, x, *ex)

        fn = jax.checkpoint(fn_) if remat else fn_

        def tick(buf, t):
            # stage 0 consumes microbatch t (clamped); others take the buffer
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            mb = jax.lax.dynamic_index_in_dim(xs, mb_idx, axis=0,
                                              keepdims=False)
            x_in = jnp.where(stage_id == 0, mb, buf)
            y = fn(params, x_in)
            # last stage's finished microbatch (zeros elsewhere / off-window)
            done = jnp.logical_and(stage_id == n_stages - 1,
                                   jnp.logical_and(t >= n_stages - 1,
                                                   t < total_ticks))
            out = jnp.where(done, y, jnp.zeros_like(y))
            # neighbor exchange: stage i -> i+1 (last stage sends nowhere;
            # ring perm keeps the collective uniform, stage 0 overwrites)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf_next = jax.lax.ppermute(y, axis, perm)
            return buf_next, out

        buf0 = jnp.zeros_like(jax.eval_shape(fn, params, xs[0]))
        _, outs = jax.lax.scan(tick, buf0, jnp.arange(total_ticks))
        # outs: (total_ticks, *mb) — microbatch m finished at tick m+n_stages-1
        outs = outs[n_stages - 1:]
        # replicate final-stage results to every pipe rank (others hold 0)
        outs = jax.lax.psum(outs, axis)
        return outs

    mapped = shard_map(per_device, mesh=mesh,
                       in_specs=(param_specs, P()) + extra_specs,
                       out_specs=P(),
                       axis_names={axis},
                       check_vma=False)
    # partial-manual shard_map (manual 'pipe', auto tp/dp axes) only traces
    # under jit; inlined for free when an outer jit (to_static) is active
    return jax.jit(mapped)(stage_params, micro_inputs, *extras)


def _pipeline_circular(stage_params, micro_inputs, stage_fn, mesh, axis,
                       remat, extras, n_virtual):
    """Interleaved (circular / virtual-pipeline) schedule.

    Chunk c = v*n_stages + d runs at device d on ring pass v. Micro-batch m
    of a group enters chunk (v, device d) at tick m + v*n_stages + d; per
    tick every device computes at most one (microbatch, chunk) pair —
    ``u = t - stage_id``, valid iff 0 <= u < n_stages*n_virtual, with
    v = u // n_stages and local microbatch m = u % n_stages. Micro-batches
    run in groups of n_stages (the in-flight window of the circular
    schedule); one lax.scan covers all groups.
    """
    n = mesh.shape[axis]
    V = n_virtual
    n_micro = micro_inputs.shape[0]
    if n_micro % n != 0:
        raise ValueError(
            f"interleaved pipeline needs n_micro ({n_micro}) divisible by "
            f"n_stages ({n})")
    n_groups = n_micro // n
    group_ticks = n * V + n - 1
    total_ticks = n_groups * group_ticks

    param_specs = jax.tree_util.tree_map(lambda _: P(None, axis), stage_params)
    extra_specs = tuple(P() for _ in extras)

    def per_device(params, xs, *ex):
        # leaves (V, 1, ...) -> (V, ...): this device's V chunks
        params = jax.tree_util.tree_map(lambda a: a[:, 0], params)
        stage_id = jax.lax.axis_index(axis)

        def fn_(p, x):
            return stage_fn(p, x, *ex)

        fn = jax.checkpoint(fn_) if remat else fn_
        p0 = jax.tree_util.tree_map(lambda a: a[0], params)
        mb_shape = jax.eval_shape(fn, p0, xs[0])

        def tick(buf, t):
            g = t // group_ticks
            tl = t % group_ticks           # tick within the group
            u = tl - stage_id              # chunk-progress index
            v = jnp.clip(u // n, 0, V - 1)
            m_local = jnp.clip(u, 0, n * V - 1) % n
            m = jnp.clip(g * n + m_local, 0, n_micro - 1)
            mb = jax.lax.dynamic_index_in_dim(xs, m, axis=0, keepdims=False)
            # device 0 takes a fresh microbatch on ring pass 0 only; later
            # passes consume the buffer arriving from device n-1
            fresh = jnp.logical_and(stage_id == 0,
                                    jnp.logical_and(u >= 0, u < n))
            x_in = jnp.where(fresh, mb, buf)
            pv = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, axis=0,
                                                       keepdims=False),
                params)
            y = fn(pv, x_in)
            # last device on the last ring pass emits finished microbatches
            done = jnp.logical_and(
                stage_id == n - 1,
                jnp.logical_and(u >= n * (V - 1), u < n * V))
            out = jnp.where(done, y, jnp.zeros_like(y))
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(y, axis, perm), out

        buf0 = jnp.zeros_like(mb_shape)
        _, outs = jax.lax.scan(tick, buf0, jnp.arange(total_ticks))
        # per group, the final n ticks emit microbatches g*n .. g*n + n - 1
        outs = outs.reshape(n_groups, group_ticks, *outs.shape[1:])[:, -n:]
        outs = outs.reshape(n_micro, *outs.shape[2:])
        return jax.lax.psum(outs, axis)

    mapped = shard_map(per_device, mesh=mesh,
                       in_specs=(param_specs, P()) + extra_specs,
                       out_specs=P(),
                       axis_names={axis},
                       check_vma=False)
    return jax.jit(mapped)(stage_params, micro_inputs, *extras)


ZB_SCHEDULES = ("ZB-H1", "ZB", "zero_bubble")
# ZB composed with the 2-chunk virtual pipeline (V placement). Kept
# separate from ZB_SCHEDULES: consumers that only know the flat H1
# ordering must fail loudly on these, not silently run H1 under a V name
# (fleet_executor.build_zbv_rank_schedules owns the V machinery).
ZBV_SCHEDULES = ("ZB-V", "ZBV")


class PipelineMicroScheduler:
    """Host-level micro-batch scheduler used by fleet.PipelineParallel for
    the eager path (schedule bookkeeping parity: FThenB / 1F1B orderings).
    The compiled path above is the performance path."""

    def __init__(self, n_stages, n_micro, schedule="1F1B"):
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.schedule = schedule

    def steps(self):
        """Yields ('F', i) / ('B', i) — plus ('W', i) for ZB-H1 — events in
        schedule order for rank-0 semantics (single-process SPMD runs the
        whole graph)."""
        if self.schedule == "FThenB":
            for i in range(self.n_micro):
                yield ("F", i)
            for i in range(self.n_micro):
                yield ("B", i)
            return
        if self.schedule in ZB_SCHEDULES or self.schedule in ZBV_SCHEDULES:
            # Host-sequential event view: the B/W split is identical for
            # flat ZB-H1 and chunked ZB-V (the V placement changes which
            # RANK owns which virtual stage — build_zbv_rank_schedules —
            # not the single-process topological order).
            yield from self._zb_h1_steps()
            return
        # n_stages=1 has no pipeline overlap: warmup must still cover
        # F(0) or the steady loop would emit B(0) before its forward
        warmup = min(max(self.n_stages - 1, 1), self.n_micro)
        for i in range(warmup):
            yield ("F", i)
        fwd = warmup
        bwd = 0
        while bwd < self.n_micro:
            if fwd < self.n_micro:
                yield ("B", bwd)
                bwd += 1
                yield ("F", fwd)
                fwd += 1
            else:
                yield ("B", bwd)
                bwd += 1

    def _zb_h1_steps(self):
        """ZB-H1 zero-bubble ordering (parity: reference
        passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62): the
        backward splits into B (input grads — on the critical path, sent
        upstream immediately) and W (weight grads — free to slide into
        bubbles). Warmup forwards as 1F1B; steady state interleaves F/B;
        W fills the cooldown slots that 1F1B leaves idle, deferring all
        remaining W to the tail."""
        warmup = min(max(self.n_stages - 1, 1), self.n_micro)
        for i in range(warmup):
            yield ("F", i)
        fwd = warmup
        bwd = 0
        w_done = 0
        while bwd < self.n_micro:
            yield ("B", bwd)
            bwd += 1
            if fwd < self.n_micro:
                yield ("F", fwd)
                fwd += 1
            elif w_done < bwd - 1:
                # cooldown bubble: retire a deferred weight grad
                yield ("W", w_done)
                w_done += 1
        while w_done < self.n_micro:
            yield ("W", w_done)
            w_done += 1
