"""In-graph pipeline parallelism over the 'pipe' mesh axis.

Parity: reference pipeline runtime — micro-batch schedules
(`fleet/meta_parallel/pipeline_parallel.py:565` 1F1B, `:1161` interleave,
static passes `passes/pipeline_scheduler_pass/`) and the P2P layer
(`pp_utils/p2p_communication.py` batched isend/irecv).

TPU-native: there is no host-driven micro-step loop with NCCL p2p. The
whole schedule is one compiled XLA program: stage weights are stacked on a
leading dim sharded over 'pipe'; a lax.scan over ticks moves activations
between neighbor stages with lax.ppermute (ICI neighbor exchange — the
send_v2/recv_v2 analog); jax AD differentiates the scan, so the backward
pipeline (reverse ppermute chain) is derived, not hand-scheduled. Memory is
controlled with jax.checkpoint per stage (the reference needs 1F1B for
this; remat-in-scan achieves the same peak-activation bound, with the
schedule left to the XLA scheduler).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_forward", "stack_stage_params", "PipelineMicroScheduler"]

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage_params):
    """List (len n_stages) of identical-structure pytrees -> stacked pytree
    (leaves gain a leading n_stages dim to shard over 'pipe')."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                  *per_stage_params)


def pipeline_forward(stage_params, micro_inputs, stage_fn: Callable, mesh,
                     axis: str = PIPE_AXIS, remat: bool = True,
                     other_specs=P()):
    """Run `stage_fn(params, x) -> y` as an n_stages-deep pipeline.

    stage_params: pytree, leaves (n_stages, ...) — sharded over `axis`.
    micro_inputs: (n_micro, *mb_shape) — replicated over `axis` (stage 0
        consumes them; ppermute forwards activations down the chain).
    Returns (n_micro, *mb_shape) outputs of the final stage, replicated
    over `axis` (zero-padded contributions psum-gathered).

    Differentiable end-to-end: jax.grad of a loss on the returned outputs
    yields the reverse pipeline automatically.
    """
    n_stages = mesh.shape[axis]
    n_micro = micro_inputs.shape[0]
    total_ticks = n_micro + n_stages - 1

    def spec_like(tree, lead):
        return jax.tree_util.tree_map(lambda _: P(*( (lead,) )), tree)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    in_spec = P()     # microbatches replicated across pipe
    out_spec = P()

    def per_device(params, xs):
        # params leaves: (1, ...) — this device's stage; squeeze lead dim
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage_id = jax.lax.axis_index(axis)
        fn = jax.checkpoint(stage_fn) if remat else stage_fn

        def tick(buf, t):
            # stage 0 consumes microbatch t (clamped); others take the buffer
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            mb = jax.lax.dynamic_index_in_dim(xs, mb_idx, axis=0,
                                              keepdims=False)
            x_in = jnp.where(stage_id == 0, mb, buf)
            y = fn(params, x_in)
            # last stage's finished microbatch (zeros elsewhere / off-window)
            done = jnp.logical_and(stage_id == n_stages - 1,
                                   jnp.logical_and(t >= n_stages - 1,
                                                   t < total_ticks))
            out = jnp.where(done, y, jnp.zeros_like(y))
            # neighbor exchange: stage i -> i+1 (last stage sends nowhere;
            # ring perm keeps the collective uniform, stage 0 overwrites)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf_next = jax.lax.ppermute(y, axis, perm)
            return buf_next, out

        buf0 = jnp.zeros_like(
            jax.eval_shape(fn, params, xs[0]))
        _, outs = jax.lax.scan(tick, buf0, jnp.arange(total_ticks))
        # outs: (total_ticks, *mb) — microbatch m finished at tick m+n_stages-1
        outs = outs[n_stages - 1:]
        # replicate final-stage results to every pipe rank (others hold 0)
        outs = jax.lax.psum(outs, axis)
        return outs

    mapped = shard_map(per_device, mesh=mesh,
                       in_specs=(param_specs, in_spec),
                       out_specs=out_spec,
                       check_vma=False)
    return mapped(stage_params, micro_inputs)


class PipelineMicroScheduler:
    """Host-level micro-batch scheduler used by fleet.PipelineParallel for
    the eager path (schedule bookkeeping parity: FThenB / 1F1B orderings).
    The compiled path above is the performance path."""

    def __init__(self, n_stages, n_micro, schedule="1F1B"):
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.schedule = schedule

    def steps(self):
        """Yields ('F', i) / ('B', i) events in schedule order for rank-0
        semantics (single-process SPMD runs the whole graph)."""
        if self.schedule == "FThenB":
            for i in range(self.n_micro):
                yield ("F", i)
            for i in range(self.n_micro):
                yield ("B", i)
            return
        warmup = min(self.n_stages - 1, self.n_micro)
        for i in range(warmup):
            yield ("F", i)
        fwd = warmup
        bwd = 0
        while bwd < self.n_micro:
            if fwd < self.n_micro:
                yield ("B", bwd)
                bwd += 1
                yield ("F", fwd)
                fwd += 1
            else:
                yield ("B", bwd)
                bwd += 1
