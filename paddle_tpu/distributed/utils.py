"""Distributed utilities: sequence-parallel helpers, grad fusion bookkeeping.

Parity: reference `fleet/utils/sequence_parallel_utils.py` (ScatterOp/
GatherOp/AllGatherOp/ReduceScatterOp + Column/RowSequenceParallelLinear),
`fleet/utils/tensor_fusion_helper.py`, `fleet/utils/hybrid_parallel_util.py`.

TPU-native: the SP scatter/gather PyLayers become sharding constraints on
the sequence dim over the 'sep' axis (GSPMD inserts the all_gather /
reduce_scatter); gradient fusion into flat buffers is unnecessary — XLA
fuses the gradient psum across parameters at compile time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["scatter_to_sequence_parallel", "gather_from_sequence_parallel",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "fused_allreduce_gradients", "all_gather_parameters"]

SEP_AXIS = "sep"


def _constraint(spec):
    from .fleet.mpu import _constraint as c

    def fn(t):
        return apply_op("sp_constraint", lambda a: c(a, spec), t)
    return fn


def scatter_to_sequence_parallel(x):
    """Shard the sequence dim over 'sep' (parity: ScatterOp,
    sequence_parallel_utils.py:85)."""
    nd = len(x.shape)
    spec = P(*([None] * 0 + ["sep" if i == 1 else None for i in range(nd)])) \
        if nd >= 2 else P()
    return _constraint(P(None, SEP_AXIS) if nd == 3 else spec)(x)


def gather_from_sequence_parallel(x, need_grad=True):
    """Replicate the sequence dim (parity: GatherOp/AllGatherOp)."""
    nd = len(x.shape)
    return _constraint(P(*([None] * nd)))(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter._sequence_parallel = True if not hasattr(parameter, "__slots__") \
        else None
    return parameter


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1):
    """No-op under GSPMD (grad reduction follows sharding); kept for API
    parity (sequence_parallel_utils.py:192)."""
    return layer


class ColumnSequenceParallelLinear:
    """Factory returning a ColumnParallelLinear whose input is
    sequence-sharded (all_gather on entry emitted by GSPMD)."""

    def __new__(cls, in_features, out_features, weight_attr=None,
                has_bias=True, gather_output=False, name=None, **kw):
        from .fleet.mpu import ColumnParallelLinear
        layer = ColumnParallelLinear(in_features, out_features,
                                     weight_attr=weight_attr,
                                     has_bias=has_bias,
                                     gather_output=gather_output)
        orig_forward = layer.forward

        def forward(x):
            return orig_forward(gather_from_sequence_parallel(x))
        layer.forward = forward
        return layer


class RowSequenceParallelLinear:
    """RowParallelLinear whose output is scattered back onto the sequence
    axis (reduce_scatter emitted by GSPMD)."""

    def __new__(cls, in_features, out_features, weight_attr=None,
                has_bias=True, input_is_parallel=True, name=None, **kw):
        from .fleet.mpu import RowParallelLinear
        layer = RowParallelLinear(in_features, out_features,
                                  weight_attr=weight_attr, has_bias=has_bias,
                                  input_is_parallel=input_is_parallel)
        orig_forward = layer.forward

        def forward(x):
            return scatter_to_sequence_parallel(orig_forward(x))
        layer.forward = forward
        return layer


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Parity: hybrid_parallel_util.fused_allreduce_gradients. In-trace with
    a bound 'data' axis, pmean the grads; otherwise a no-op (GSPMD path)."""
    from .collective import _axis_in_trace
    if not _axis_in_trace("data"):
        return
    for p in parameter_list:
        if p._grad_buffer is not None:
            p._grad_buffer = jax.lax.pmean(p._grad_buffer, "data")


def all_gather_parameters(parameters):
    """Materialize replicated copies of sharded parameters (stage-3 gather)."""
    from jax.sharding import NamedSharding
    out = []
    for p in parameters:
        arr = p._data
        sh = getattr(arr, "sharding", None)
        if sh is not None and hasattr(sh, "mesh"):
            arr = jax.device_put(arr, NamedSharding(sh.mesh,
                                                    P(*([None] * arr.ndim))))
        out.append(Tensor(arr))
    return out
