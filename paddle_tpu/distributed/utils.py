"""Distributed utils (tensor fusion etc. — next milestone)."""
__all__ = []
