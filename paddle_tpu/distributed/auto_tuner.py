"""Auto-tuner: black-box distributed-config search.

Parity: reference `python/paddle/distributed/auto_tuner/` — AutoTuner
(tuner.py:21, search_once/add_cfg/resume history), pruning rules
(prune.py: prune_by_mp/pp/mbs/sharding/recompute), cost & memory models
(cost_model.py, memory_cost_model.py).

TPU-native: candidates are hybrid-mesh factorings (dp/mp/pp/sharding/
micro-batch/recompute); the memory model budgets HBM per chip (params/
grads/optimizer states divided by the sharding axes + activation
estimate), the cost model ranks by modeled step time (FLOPs over
MXU peak scaled by a parallelism-efficiency factor). The runner loop is
the user's (launch a trial, report back via add_cfg), same as the
reference's controller."""
from __future__ import annotations

import csv
import itertools
import os
from typing import Dict, List, Optional

__all__ = ["AutoTuner", "default_candidates", "prune_by_mp", "prune_by_pp",
           "prune_by_mbs", "prune_by_sharding", "prune_by_recompute",
           "memory_cost", "time_cost", "measure_on_mesh",
           "measure_user_step"]


def default_candidates(tuner_cfg):
    """Enumerate dp/mp/pp/sharding/mbs/recompute candidates for the world
    size (parity: tuner.py default search space)."""
    world = int(tuner_cfg.get("num_gpus", tuner_cfg.get("num_chips", 8)))
    gbs = int(tuner_cfg.get("global_batch_size", 32))
    cands = []
    degrees = [d for d in (1, 2, 4, 8, 16, 32, 64) if d <= world]
    for mp, pp, sharding in itertools.product(degrees, degrees, degrees):
        if world % (mp * pp) != 0:
            continue
        dp = world // (mp * pp)
        if sharding > dp:
            continue
        for mbs in (1, 2, 4, 8):
            if gbs % (dp * mbs) != 0:
                continue
            for rc in (False, True):
                cands.append({
                    "dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                    "sharding_degree": sharding, "sharding_stage": 1,
                    "micro_batch_size": mbs, "use_recompute": rc,
                })
    return cands


# --------------------------------------------------------- pruning rules
def prune_by_mp(tuner_cfg, cur_cfg, history_cfgs=()):
    """mp must divide heads and hidden size and stay intra-host-ish
    (parity: prune.py:129)."""
    mp = cur_cfg.get("mp_degree", 1)
    heads = tuner_cfg.get("model_cfg", {}).get("num_attention_heads")
    hidden = tuner_cfg.get("model_cfg", {}).get("hidden_size")
    if heads and heads % mp != 0:
        return True
    if hidden and hidden % mp != 0:
        return True
    return False


def prune_by_pp(tuner_cfg, cur_cfg, history_cfgs=()):
    """pp must divide the layer count (parity: prune.py:173)."""
    pp = cur_cfg.get("pp_degree", 1)
    layers = tuner_cfg.get("model_cfg", {}).get("num_layers")
    if layers and layers % pp != 0:
        return True
    return False


def prune_by_mbs(tuner_cfg, cur_cfg, history_cfgs=()):
    """micro batch must divide the local batch (parity: prune.py:307)."""
    gbs = int(tuner_cfg.get("global_batch_size", 32))
    dp = cur_cfg.get("dp_degree", 1)
    mbs = cur_cfg.get("micro_batch_size", 1)
    if gbs % dp != 0:
        return True
    local = gbs // dp
    return local % mbs != 0


def prune_by_sharding(tuner_cfg, cur_cfg, history_cfgs=()):
    """sharding degree divides dp (parity: prune.py:395)."""
    dp = cur_cfg.get("dp_degree", 1)
    sh = cur_cfg.get("sharding_degree", 1)
    return sh > 1 and dp % sh != 0


def prune_by_recompute(tuner_cfg, cur_cfg, history_cfgs=()):
    """If a no-recompute run already fit in memory, recompute=True can only
    be slower (parity: prune.py:486)."""
    if not cur_cfg.get("use_recompute", False):
        return False
    for h in history_cfgs:
        if (not h.get("use_recompute", False)
                and h.get("mp_degree") == cur_cfg.get("mp_degree")
                and h.get("pp_degree") == cur_cfg.get("pp_degree")
                and h.get("max_mem_usage") not in (None, "OOM")
                and h.get("time", -1) > 0):
            return True
    return False


_PRUNES = [prune_by_mp, prune_by_pp, prune_by_mbs, prune_by_sharding,
           prune_by_recompute]


# ------------------------------------------------------------ cost models
def memory_cost(tuner_cfg, cfg):
    """Modeled HBM bytes per chip (parity: memory_cost_model.py)."""
    m = tuner_cfg.get("model_cfg", {})
    L = m.get("num_layers", 32)
    h = m.get("hidden_size", 4096)
    inter = m.get("intermediate_size", 4 * h)
    vocab = m.get("vocab_size", 32000)
    seq = m.get("seq_length", 2048)
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    sh = max(cfg.get("sharding_degree", 1), 1)
    mbs = cfg.get("micro_batch_size", 1)
    params = (L * (4 * h * h + 3 * h * inter) / (mp * pp)
              + vocab * h / mp)
    # bf16 params + fp32 grads-and-adam-states sharded over `sh`
    state_bytes = params * 2 + params * 12 / sh
    act = mbs * seq * h * (L / pp) * (4 if cfg.get("use_recompute") else 24)
    return state_bytes + act * 2


def time_cost(tuner_cfg, cfg):
    """Modeled step time (relative units; parity: cost_model.py)."""
    m = tuner_cfg.get("model_cfg", {})
    L = m.get("num_layers", 32)
    h = m.get("hidden_size", 4096)
    vocab = m.get("vocab_size", 32000)
    seq = m.get("seq_length", 2048)
    gbs = int(tuner_cfg.get("global_batch_size", 32))
    world = int(tuner_cfg.get("num_gpus", tuner_cfg.get("num_chips", 8)))
    flops = 6.0 * (12 * L * h * h + vocab * h) * gbs * seq
    if cfg.get("use_recompute"):
        flops *= 4.0 / 3.0
    # parallelism efficiency: mp pays ICI collectives, pp pays bubble
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    mbs = cfg.get("micro_batch_size", 1)
    dp = cfg.get("dp_degree", 1)
    n_micro = max(gbs // (dp * mbs), 1)
    eff = (1.0 - 0.05 * (mp > 1) - 0.02 * max(mp - 2, 0) / 2)
    eff *= n_micro / (n_micro + pp - 1)          # pipeline bubble
    return flops / (world * max(eff, 1e-3))


def measure_on_mesh(tuner_cfg, cfg, iters=3):
    """MEASURE a candidate on the live device mesh (VERDICT r2 #9: the
    reference tuner's value is its measure-prune loop, tuner.py's
    controller launching real trials — analytic models only order the
    search).

    Proxy trial: a GSPMD-sharded two-matmul train step on a
    ('data', 'model') mesh with data = dp and model = mp*pp (the pipeline
    axis folds into the model axis for the proxy — the proxy measures
    layout/collective cost, not bubble structure, which the makespan
    model in fleet_executor covers). Returns measured wall-clock step
    time and the peak-memory reading from the device memory-stats API.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..device import reset_max_memory_allocated
    try:   # per-trial peak, not the process-lifetime max
        reset_max_memory_allocated()
    except Exception:
        pass
    dp = int(cfg.get("dp_degree", 1))
    mp = int(cfg.get("mp_degree", 1))
    pp = int(cfg.get("pp_degree", 1))
    mbs = int(cfg.get("micro_batch_size", 1))
    need = dp * mp * pp
    devs = jax.devices()
    if need > len(devs):
        return {"time": -1, "max_mem_usage": "SKIP",
                "error": f"needs {need} devices, have {len(devs)}"}
    model_ax = mp * pp
    mesh = Mesh(np.asarray(devs[:need]).reshape(dp, model_ax),
                ("data", "model"))
    h = 128 * model_ax            # keep the sharded dim divisible
    b = max(dp * mbs * 2, dp)
    rng = np.random.RandomState(0)
    w1 = jax.device_put(jnp.asarray(rng.randn(h, 2 * h), jnp.float32) * 0.02,
                        NamedSharding(mesh, P(None, "model")))
    w2 = jax.device_put(jnp.asarray(rng.randn(2 * h, h), jnp.float32) * 0.02,
                        NamedSharding(mesh, P("model", None)))
    x = jax.device_put(jnp.asarray(rng.randn(b, h), jnp.float32),
                       NamedSharding(mesh, P("data", None)))
    y = jax.device_put(jnp.asarray(rng.randn(b, h), jnp.float32),
                       NamedSharding(mesh, P("data", None)))

    def loss_fn(params, x, y):
        w1_, w2_ = params
        pred = jnp.maximum(x @ w1_, 0) @ w2_
        return ((pred - y) ** 2).mean()

    @jax.jit
    def step(params, x, y):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        return jax.tree_util.tree_map(lambda p, gg: p - 1e-3 * gg,
                                      params, g), loss

    params = (w1, w2)
    params, loss = step(params, x, y)          # compile
    np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, loss = step(params, x, y)
    # host fetch, not block_until_ready: over relayed transports (axon)
    # block_until_ready does not actually block (see kernels/timing.py);
    # the steps themselves serialize through the params chain
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / iters

    from ..device import max_memory_allocated
    try:
        peak = int(max_memory_allocated())
    except Exception:
        peak = 0
    return {"time": dt, "max_mem_usage": peak, "measured": True}


def measure_user_step(train_step_builder, iters=3):
    """Trial function that measures the USER'S model, not a proxy
    (VERDICT r3 item 7; parity: the reference tuner launches the user's
    actual training command per trial, auto_tuner/tuner.py controller).

    `train_step_builder(tuner_cfg, cfg) -> step` builds the user's model
    + optimizer under the candidate config (mesh/shardings chosen by the
    user from cfg's dp/mp/pp/sharding degrees) and returns a zero-arg
    callable running ONE step. The tuner compiles via a warmup call,
    then times `iters` steps; builder/step failures are recorded as
    SKIP/OOM instead of aborting the search."""
    import time

    def trial(tuner_cfg, cfg):
        import jax
        from ..device import reset_max_memory_allocated
        try:   # per-trial peak, not the process-lifetime max
            reset_max_memory_allocated()
        except Exception:
            pass
        try:
            step = train_step_builder(tuner_cfg, cfg)
        except Exception as e:
            return {"time": -1, "max_mem_usage": "SKIP",
                    "error": repr(e)}
        try:
            import numpy as _np
            import jax.numpy as _jnp

            def _sync(o):
                # host fetch of ONE element PER ARRAY leaf — the only
                # sync that also works over relayed transports (see
                # kernels/timing.py). Every device leaf must be
                # awaited (a host-scalar first leaf would complete
                # instantly and collapse dt to dispatch time); slicing
                # on device first keeps large leaves (e.g. returned
                # params) from turning the timed region into a full
                # D2H transfer.
                for leaf in jax.tree_util.tree_leaves(o):
                    if hasattr(leaf, "addressable_shards") or hasattr(
                            leaf, "device_buffer") or hasattr(leaf, "devices"):
                        _np.asarray(_jnp.ravel(leaf)[0] if getattr(
                            leaf, "ndim", 0) else leaf)

            _sync(step())                     # warmup: traces + compiles
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = step()
            _sync(out)
            dt = (time.perf_counter() - t0) / iters
        except Exception as e:
            oom = "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)
            return {"time": -1,
                    "max_mem_usage": "OOM" if oom else "SKIP",
                    "error": repr(e)}
        from ..device import max_memory_allocated
        try:
            peak = int(max_memory_allocated())
        except Exception:
            peak = 0
        return {"time": dt, "max_mem_usage": peak, "measured": True,
                "user_model": True}
    return trial


class AutoTuner:
    """Parity: tuner.py:21 AutoTuner. Usage:

        tuner = AutoTuner(cfg)
        while True:
            trial = tuner.search_once()
            if trial is None: break
            metrics = run_trial(trial)        # user-side launch
            trial.update(metrics)             # {'time': ..., 'max_mem_usage'}
            tuner.add_cfg(trial)
        best = tuner.best_cfg()
    """

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = dict(tuner_cfg)
        self.history_cfgs: List[Dict] = []
        cands = tuner_cfg.get("candidates") or default_candidates(tuner_cfg)
        mem_limit = tuner_cfg.get("max_mem_per_chip_gb")
        pruned = []
        for c in cands:
            if any(p(self.tuner_cfg, c, self.history_cfgs) for p in _PRUNES):
                continue
            c = dict(c)
            c["modeled_time"] = time_cost(self.tuner_cfg, c)
            c["modeled_mem"] = memory_cost(self.tuner_cfg, c)
            if mem_limit and c["modeled_mem"] > mem_limit * (1 << 30):
                continue
            pruned.append(c)
        # best-modeled-first search order
        self.candidates = sorted(pruned, key=lambda c: c["modeled_time"])
        self.cur_task_id = 0

    def search_once(self) -> Optional[Dict]:
        while self.cur_task_id < len(self.candidates):
            cfg = self.candidates[self.cur_task_id]
            self.cur_task_id += 1
            if any(p(self.tuner_cfg, cfg, self.history_cfgs)
                   for p in _PRUNES):
                continue
            return dict(cfg)
        return None

    def add_cfg(self, cfg: Dict):
        self.history_cfgs.append(dict(cfg))

    def best_cfg(self) -> Optional[Dict]:
        done = [c for c in self.history_cfgs
                if c.get("time", -1) > 0 and c.get("max_mem_usage") != "OOM"]
        return min(done, key=lambda c: c["time"]) if done else None

    # ---- measure-and-refine loop (VERDICT r2 #9) --------------------------
    def _capacity_bytes(self) -> Optional[int]:
        """Per-chip memory budget for OOM prediction: the configured cap,
        else the device memory-stats bytes_limit when published."""
        cap_gb = self.tuner_cfg.get("max_mem_per_chip_gb")
        if cap_gb:
            return int(cap_gb * (1 << 30))
        try:
            from ..device import memory_stats
            limit = memory_stats().get("bytes_limit")
            return int(limit) if limit else None
        except Exception:
            return None

    def tune(self, trial_fn=None, max_trials: Optional[int] = None,
             early_stop_no_improve: Optional[int] = None,
             train_step_fn=None) -> Optional[Dict]:
        """Drive the search with REAL measurements (parity: the reference
        controller loop, auto_tuner/tuner.py — launch trial, record
        metrics, prune, continue).

        Measurement priority (VERDICT r3 item 7): `train_step_fn` — the
        USER's model: a builder `(tuner_cfg, cfg) -> step_callable` timed
        via `measure_user_step` — then explicit `trial_fn`, then the
        `measure_on_mesh` proxy as last resort. Candidates whose modeled
        memory exceeds the per-chip budget (configured cap or the
        memory-stats API's bytes_limit) are recorded as predicted OOM
        without being launched. Returns the measured-fastest config."""
        if train_step_fn is not None:
            trial_fn = measure_user_step(train_step_fn)
        trial_fn = trial_fn or measure_on_mesh
        cap = self._capacity_bytes()
        trials = 0
        best_t = float("inf")
        stale = 0
        while max_trials is None or trials < max_trials:
            cfg = self.search_once()
            if cfg is None:
                break
            if cap is not None and cfg.get("modeled_mem", 0) > cap:
                cfg.update({"time": -1, "max_mem_usage": "OOM",
                            "oom_predicted": True})
                self.add_cfg(cfg)
                continue
            metrics = trial_fn(self.tuner_cfg, cfg)
            cfg.update(metrics)
            self.add_cfg(cfg)
            trials += 1
            t = cfg.get("time", -1)
            if 0 < t < best_t:
                best_t, stale = t, 0
            else:
                stale += 1
                if early_stop_no_improve and stale >= early_stop_no_improve:
                    break
        return self.best_cfg()

    # ---- history persistence (parity: resume_form_history, tuner.py:75)
    def save_history(self, path="./history.csv"):
        if not self.history_cfgs:
            return
        keys = sorted({k for c in self.history_cfgs for k in c})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for c in self.history_cfgs:
                w.writerow(c)

    def resume_form_history(self, history_csv_path="./history.csv"):
        if not os.path.exists(history_csv_path):
            return False
        with open(history_csv_path) as f:
            for row in csv.DictReader(f):
                parsed = {}
                for k, v in row.items():
                    if v in ("True", "False"):   # bools round-trip as text
                        parsed[k] = v == "True"
                        continue
                    try:
                        parsed[k] = int(v)
                    except (TypeError, ValueError):
                        try:
                            parsed[k] = float(v)
                        except (TypeError, ValueError):
                            parsed[k] = v
                self.history_cfgs.append(parsed)
        return True

    resume_from_history = resume_form_history  # un-typo'd alias
