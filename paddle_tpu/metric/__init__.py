"""Metrics. Parity: reference python/paddle/metric/metrics.py
(Metric base, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy. Parity: paddle.metric.accuracy."""
    import jax.numpy as jnp
    from ..ops.dispatch import apply_op

    def _f(pred, lab):
        topk_idx = jnp.argsort(-pred, axis=-1)[..., :k]
        if lab.ndim == pred.ndim:
            lab_ = lab
        else:
            lab_ = lab[..., None]
        hit = jnp.any(topk_idx == lab_, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op("accuracy", _f, input, label)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        import jax.numpy as jnp
        pred_np = np.asarray(pred._data if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._data if isinstance(label, Tensor) else label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim + 1 == idx.ndim:
            label_np = label_np[..., None]
        correct = (idx == label_np)
        return Tensor(np.asarray(correct, np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._data if isinstance(correct, Tensor) else correct)
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += c.shape[0]
            accs.append(float(num) / c.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_bin = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1).astype(np.int64)
        self.tp += int(((pred_bin == 1) & (l == 1)).sum())
        self.fp += int(((pred_bin == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_bin = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1).astype(np.int64)
        self.tp += int(((pred_bin == 1) & (l == 1)).sum())
        self.fn += int(((pred_bin == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        if p.ndim == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds from high to low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
