"""Distribution tail: heavy-tailed/count distributions, the Transform
zoo, TransformedDistribution, Independent, MultivariateNormal.

Parity: reference `python/paddle/distribution/` — poisson.py, cauchy.py,
chi2.py, student_t.py, binomial.py, continuous_bernoulli.py,
multivariate_normal.py, independent.py, transform.py (Abs/Affine/Chain/
Exp/Independent/Power/Reshape/Sigmoid/Softmax/Stack/StickBreaking/Tanh),
transformed_distribution.py, exponential_family.py.

TPU-native: log-probs/entropies are jnp closed forms routed through
apply_op (differentiable wrt Tensor params); sampling draws from the
framework PRNG stream (reproducible under paddle.seed)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op
from . import (Distribution, Gamma, Normal, _arr, _key, kl_divergence,
               register_kl)

__all__ = [
    "Poisson", "Cauchy", "Chi2", "StudentT", "Binomial",
    "ContinuousBernoulli", "MultivariateNormal", "ExponentialFamily",
    "Independent", "TransformedDistribution", "Transform", "AbsTransform",
    "AffineTransform", "ChainTransform", "ExpTransform",
    "IndependentTransform", "PowerTransform", "ReshapeTransform",
    "SigmoidTransform", "SoftmaxTransform", "StackTransform",
    "StickBreakingTransform", "TanhTransform", "LKJCholesky",
]


class ExponentialFamily(Distribution):
    """Base marker for exponential-family distributions (the reference
    uses it to derive entropy via Bregman divergence; subclasses here
    provide closed-form entropies directly)."""


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self._rate_p = rate if isinstance(rate, Tensor) else None
        self.rate = _arr(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        out = jax.random.poisson(_key(), self.rate,
                                 tuple(shape) + self.rate.shape)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def _f(r, v):
            return v * jnp.log(r) - r - jax.scipy.special.gammaln(v + 1)
        return apply_op("poisson_log_prob", _f,
                        self._param(self._rate_p, self.rate), value)

    def entropy(self):
        # series: rate*(1-log rate) + exp(-rate) * sum_k rate^k log(k!)/k!
        # with a RATE-DEPENDENT support bound (the summand peaks near
        # k ~ rate; reference poisson.py enumerates bounded support too)
        import numpy as _np
        rmax = float(_np.max(_np.asarray(self.rate)))
        kmax = int(max(30, _np.ceil(rmax + 12 * _np.sqrt(rmax) + 10)))

        def _f(r):
            ks = jnp.arange(1.0, kmax + 1.0)
            lgk = jax.scipy.special.gammaln(ks + 1)
            # keep -r inside the exponent: the summand alone overflows
            # f32 near k ~ r for r >~ 90
            terms = jnp.exp(ks[(None,) * r.ndim + (slice(None),)]
                            * jnp.log(r)[..., None] - r[..., None]
                            - lgk) * lgk
            return r * (1 - jnp.log(r)) + terms.sum(-1)
        return apply_op("poisson_entropy", _f,
                        self._param(self._rate_p, self.rate))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self._scale_p = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        t = self.rsample(shape)
        t.stop_gradient = True
        return Tensor(t._data)

    def rsample(self, shape=()):
        def _f(l, s):
            u = jax.random.uniform(_key(), self._extend(shape),
                                   minval=1e-6, maxval=1 - 1e-6)
            return l + s * jnp.tan(jnp.pi * (u - 0.5))
        return apply_op("cauchy_rsample", _f,
                        self._param(self._loc_p, self.loc),
                        self._param(self._scale_p, self.scale))

    def log_prob(self, value):
        def _f(l, s, v):
            return (-jnp.log(jnp.pi) - jnp.log(s)
                    - jnp.log1p(((v - l) / s) ** 2))
        return apply_op("cauchy_log_prob", _f,
                        self._param(self._loc_p, self.loc),
                        self._param(self._scale_p, self.scale), value)

    def cdf(self, value):
        def _f(l, s, v):
            return jnp.arctan((v - l) / s) / jnp.pi + 0.5
        return apply_op("cauchy_cdf", _f,
                        self._param(self._loc_p, self.loc),
                        self._param(self._scale_p, self.scale), value)

    def entropy(self):
        def _f(s):
            return jnp.log(4 * jnp.pi) + jnp.log(s)
        return apply_op("cauchy_entropy", _f,
                        self._param(self._scale_p, self.scale))


class Chi2(Gamma):
    """Chi-squared = Gamma(df/2, rate=1/2)."""

    def __init__(self, df, name=None):
        self._df_p = df if isinstance(df, Tensor) else None
        self.df = _arr(df)
        super().__init__(self.df / 2.0, 0.5)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self._df_p = df if isinstance(df, Tensor) else None
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self._scale_p = scale if isinstance(scale, Tensor) else None
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1,
                                jnp.broadcast_to(self.loc,
                                                 self._batch_shape),
                                jnp.nan))

    @property
    def variance(self):
        v = jnp.where(self.df > 2, self.df / (self.df - 2), jnp.inf)
        return Tensor(jnp.where(self.df > 1,
                                self.scale ** 2 * v, jnp.nan))

    def sample(self, shape=()):
        t = self.rsample(shape)
        t.stop_gradient = True
        return Tensor(t._data)

    def rsample(self, shape=()):
        def _f(df, l, s):
            z = jax.random.t(_key(), df, self._extend(shape))
            return l + s * z
        return apply_op("student_t_rsample", _f,
                        self._param(self._df_p, self.df),
                        self._param(self._loc_p, self.loc),
                        self._param(self._scale_p, self.scale))

    def log_prob(self, value):
        def _f(df, l, s, v):
            y = (v - l) / s
            lg = jax.scipy.special.gammaln
            return (lg((df + 1) / 2) - lg(df / 2)
                    - 0.5 * jnp.log(df * jnp.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(y ** 2 / df))
        return apply_op("student_t_log_prob", _f,
                        self._param(self._df_p, self.df),
                        self._param(self._loc_p, self.loc),
                        self._param(self._scale_p, self.scale), value)

    def entropy(self):
        def _f(df, s):
            dig = jax.scipy.special.digamma
            lg = jax.scipy.special.gammaln
            return (jnp.log(s) + (df + 1) / 2 * (dig((df + 1) / 2)
                                                 - dig(df / 2))
                    + 0.5 * jnp.log(df) + jax.scipy.special.betaln(
                        df / 2, jnp.asarray(0.5)))
        return apply_op("student_t_entropy", _f,
                        self._param(self._df_p, self.df),
                        self._param(self._scale_p, self.scale))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self._probs_p = probs if isinstance(probs, Tensor) else None
        self.total_count = _arr(total_count)
        self.probs = _arr(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs.shape))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        out = jax.random.binomial(
            _key(), jnp.broadcast_to(self.total_count, self._batch_shape),
            jnp.broadcast_to(self.probs, self._batch_shape),
            self._extend(shape))
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def _f(p, v):
            n = self.total_count
            lg = jax.scipy.special.gammaln
            logc = lg(n + 1) - lg(v + 1) - lg(n - v + 1)
            return (logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p))
        return apply_op("binomial_log_prob", _f,
                        self._param(self._probs_p, self.probs), value)

    def entropy(self):
        """Exact entropy by summation over the support (reference
        binomial.py does the same)."""
        def _f(p):
            n = jnp.broadcast_to(self.total_count, self._batch_shape)
            nmax = int(jnp.max(n))
            ks = jnp.arange(0.0, nmax + 1.0)
            lg = jax.scipy.special.gammaln
            kshape = (None,) * len(self._batch_shape) + (slice(None),)
            logc = (lg(n[..., None] + 1) - lg(ks[kshape] + 1)
                    - lg(n[..., None] - ks[kshape] + 1))
            logp = (logc + ks[kshape] * jnp.log(p[..., None])
                    + (n[..., None] - ks[kshape]) * jnp.log1p(-p[..., None]))
            valid = ks[kshape] <= n[..., None]
            pk = jnp.where(valid, jnp.exp(logp), 0.0)
            return -(pk * jnp.where(valid, logp, 0.0)).sum(-1)
        return apply_op("binomial_entropy", _f,
                        self._param(self._probs_p, self.probs))


class ContinuousBernoulli(Distribution):
    """CB(lambda): density lambda^x (1-lambda)^(1-x) * C(lambda) on
    [0, 1] (reference continuous_bernoulli.py)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self._probs_p = probs if isinstance(probs, Tensor) else None
        self.probs = _arr(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_norm(self, p):
        # C(p) = 2*atanh(1-2p)/(1-2p) for p != 0.5, = 2 at p = 0.5
        lo, hi = self._lims
        safe = jnp.where((p > lo) & (p < hi), 0.25, p)
        c = (2 * jnp.arctanh(1 - 2 * safe)) / (1 - 2 * safe)
        # 2nd-order Taylor of 2*atanh(1-2p)/(1-2p) around p=1/2:
        # with t = 1-2p, = 2 + 2t^2/3 = 2 + (8/3)(p-1/2)^2
        taylor = 2.0 + (8.0 / 3.0) * (p - 0.5) ** 2
        return jnp.log(jnp.where((p > lo) & (p < hi), taylor, c))

    def sample(self, shape=()):
        t = self.rsample(shape)
        t.stop_gradient = True
        return Tensor(t._data)

    def rsample(self, shape=()):
        def _f(p):
            u = jax.random.uniform(_key(), self._extend(shape),
                                   minval=1e-6, maxval=1 - 1e-6)
            lo, hi = self._lims
            mid = (p > lo) & (p < hi)
            safe = jnp.where(mid, 0.25, p)
            x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                 / (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where(mid, u, x)
        return apply_op("cb_rsample", _f,
                        self._param(self._probs_p, self.probs))

    def log_prob(self, value):
        def _f(p, v):
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_norm(p))
        return apply_op("cb_log_prob", _f,
                        self._param(self._probs_p, self.probs), value)

    @property
    def mean(self):
        p = self.probs
        lo, hi = self._lims
        mid = (p > lo) & (p < hi)
        safe = jnp.where(mid, 0.25, p)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return Tensor(jnp.where(mid, 0.5, m))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 precision_matrix=None, name=None):
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self.loc = _arr(loc)
        if scale_tril is not None:
            self._tril_p = scale_tril if isinstance(scale_tril, Tensor) \
                else None
            self.scale_tril = _arr(scale_tril)
        elif covariance_matrix is not None:
            self._tril_p = None
            self.scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        elif precision_matrix is not None:
            self._tril_p = None
            cov = jnp.linalg.inv(_arr(precision_matrix))
            self.scale_tril = jnp.linalg.cholesky(cov)
        else:
            raise ValueError("one of covariance_matrix / scale_tril / "
                             "precision_matrix is required")
        super().__init__(tuple(self.loc.shape[:-1]),
                         tuple(self.loc.shape[-1:]))

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def covariance_matrix(self):
        L = self.scale_tril
        return Tensor(L @ jnp.swapaxes(L, -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.sum(self.scale_tril ** 2, axis=-1))

    def sample(self, shape=()):
        t = self.rsample(shape)
        t.stop_gradient = True
        return Tensor(t._data)

    def rsample(self, shape=()):
        def _f(l, L):
            z = jax.random.normal(
                _key(), tuple(shape) + self._batch_shape
                + self._event_shape)
            return l + jnp.einsum("...ij,...j->...i", L, z)
        return apply_op("mvn_rsample", _f,
                        self._param(self._loc_p, self.loc),
                        self._param(self._tril_p, self.scale_tril))

    def log_prob(self, value):
        def _f(l, L, v):
            d = v - l
            # solve L y = d  (triangular)
            y = jax.scipy.linalg.solve_triangular(
                L, d[..., None], lower=True)[..., 0]
            k = l.shape[-1]
            half_logdet = jnp.log(
                jnp.abs(jnp.diagonal(L, axis1=-2, axis2=-1))).sum(-1)
            return (-0.5 * (y ** 2).sum(-1) - half_logdet
                    - 0.5 * k * math.log(2 * math.pi))
        return apply_op("mvn_log_prob", _f,
                        self._param(self._loc_p, self.loc),
                        self._param(self._tril_p, self.scale_tril), value)

    def entropy(self):
        def _f(L):
            k = L.shape[-1]
            half_logdet = jnp.log(
                jnp.abs(jnp.diagonal(L, axis1=-2, axis2=-1))).sum(-1)
            return half_logdet + 0.5 * k * (1 + math.log(2 * math.pi))
        return apply_op("mvn_entropy", _f,
                        self._param(self._tril_p, self.scale_tril))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    Lp, Lq = p.scale_tril, q.scale_tril
    k = Lp.shape[-1]
    M = jax.scipy.linalg.solve_triangular(Lq, Lp, lower=True)
    tr = (M ** 2).sum((-1, -2))
    d = q.loc - p.loc
    y = jax.scipy.linalg.solve_triangular(Lq, d[..., None],
                                          lower=True)[..., 0]
    maha = (y ** 2).sum(-1)
    logdet = (jnp.log(jnp.abs(jnp.diagonal(Lq, axis1=-2, axis2=-1))).sum(-1)
              - jnp.log(jnp.abs(jnp.diagonal(Lp, axis1=-2,
                                             axis2=-1))).sum(-1))
    return Tensor(0.5 * (tr + maha - k) + logdet)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    num = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    return Tensor(jnp.log(num / (4 * p.scale * q.scale)))


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    r1, r2 = p.rate, q.rate
    return Tensor(r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2)


# ---------------------------------------------------------------- transforms

class Transform:
    """Bijector base. Parity: paddle.distribution.Transform
    (forward / inverse / forward_log_det_jacobian)."""

    _domain_event_dim = 0

    def forward(self, x):
        return apply_op(type(self).__name__ + ".fwd", self._forward, x)

    def inverse(self, y):
        return apply_op(type(self).__name__ + ".inv", self._inverse, y)

    def forward_log_det_jacobian(self, x):
        return apply_op(type(self).__name__ + ".fldj", self._fldj, x)

    def inverse_log_det_jacobian(self, y):
        def _f(yv):
            return -self._fldj(self._inverse(yv))
        return apply_op(type(self).__name__ + ".ildj", _f, y)

    def __call__(self, x):
        return self.forward(x)

    # subclasses implement array-level versions
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right-inverse (reference returns the positive branch)

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-6, 1 - 1e-6))

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _domain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("softmax is not a bijection; no ldj")


class StickBreakingTransform(Transform):
    _domain_event_dim = 1

    def _forward(self, x):
        # R^{K-1} -> simplex^K
        offset = x.shape[-1] - jnp.arange(x.shape[-1])
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,))], -1)
        cum = jnp.cumprod(1 - z, axis=-1)
        cumpad = jnp.concatenate([jnp.ones(z.shape[:-1] + (1,)), cum], -1)
        return zpad * cumpad

    def _inverse(self, y):
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rem = 1 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,)), cum[..., :-1]], -1)
        z = y[..., :-1] / rem
        offset = (y.shape[-1] - 1) - jnp.arange(y.shape[-1] - 1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1])
        z = jax.nn.sigmoid(x - jnp.log(offset))
        # per stick k: log z_k + log(1-z_k) + sum_{j<k} log(1-z_j)
        prior = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,)),
             jnp.cumsum(jnp.log1p(-z), -1)[..., :-1]], -1)
        return (jnp.log(z) + jnp.log1p(-z) + prior).sum(-1)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_dim = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._domain_event_dim = max(
            (t._domain_event_dim for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        # reduce every per-transform ldj to the chain's batch frame before
        # summing (mixed event dims would otherwise broadcast wrongly)
        batch_ndim = x.ndim - self._domain_event_dim
        total = None
        for t in self.transforms:
            ld = t._fldj(x)
            if ld.ndim > batch_ndim:
                ld = ld.sum(axis=tuple(range(batch_ndim, ld.ndim)))
            total = ld if total is None else total + ld
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_dim = base._domain_event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return ld.sum(axis=tuple(range(ld.ndim - self.rank, ld.ndim)))


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _pieces(self, x):
        return [jnp.take(x, i, axis=self.axis)
                for i in range(len(self.transforms))]

    def _forward(self, x):
        return jnp.stack([t._forward(p) for t, p in
                          zip(self.transforms, self._pieces(x))],
                         axis=self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(p) for t, p in
                          zip(self.transforms, self._pieces(y))],
                         axis=self.axis)

    def _fldj(self, x):
        return jnp.stack([t._fldj(p) for t, p in
                          zip(self.transforms, self._pieces(x))],
                         axis=self.axis)


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims (reference
    independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def _f(a):
            return a.sum(axis=tuple(range(a.ndim - self.rank, a.ndim)))
        return apply_op("independent_sum", _f, lp)

    def entropy(self):
        ent = self.base.entropy()

        def _f(a):
            return a.sum(axis=tuple(range(a.ndim - self.rank, a.ndim)))
        return apply_op("independent_sum", _f, ent)


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms
    (reference transformed_distribution.py)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        try:
            t = self.rsample(shape)
        except NotImplementedError:
            # non-reparameterizable base: detached sample + forward
            t = self.base.sample(shape)
            for tr in self.transforms:
                t = tr.forward(t)
        t.stop_gradient = True
        return Tensor(t._data)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for tr in self.transforms:
            x = tr.forward(x)
        return x

    def log_prob(self, value):
        y = value

        def _chain(v):
            lds = []
            for tr in reversed(self.transforms):
                x = tr._inverse(v)
                lds.append(tr._fldj(x))
                v = x
            # v is now in the base frame: reduce every ldj to the base
            # batch shape before summing
            batch_ndim = v.ndim - len(self.base.event_shape)
            ldj = jnp.zeros(())
            for ld in lds:
                if ld.ndim > batch_ndim:
                    ld = ld.sum(axis=tuple(range(batch_ndim, ld.ndim)))
                ldj = ldj + ld
            return v, ldj

        def _f(v):
            x, ldj = _chain(v)
            return x, ldj
        x_t, ldj_t = apply_op("td_pullback", _f,
                              y if isinstance(y, Tensor) else
                              Tensor(jnp.asarray(y)))
        base_lp = self.base.log_prob(x_t)

        def _sub(a, b):
            return a - b
        return apply_op("td_log_prob", _sub, base_lp, ldj_t)


class LKJCholesky(Distribution):
    """Cholesky factors of LKJ-distributed correlation matrices (parity:
    reference distribution/lkj_cholesky.py, onion construction)."""

    def __init__(self, dim=2, concentration=1.0, sample_method="onion",
                 name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self._conc_p = concentration if isinstance(concentration, Tensor) \
            else None
        self.dim = int(dim)
        self.concentration = _arr(concentration)
        self.sample_method = sample_method
        super().__init__(tuple(self.concentration.shape),
                         (self.dim, self.dim))

    def sample(self, shape=()):
        d = self.dim
        conc = self.concentration
        batch = tuple(shape) + tuple(conc.shape)
        key = _key()
        import jax as _jax
        ks = _jax.random.split(key, 2 * d)   # distinct key per draw
        # onion: row i (1-indexed) is a scaled point on the sphere
        rows = [jnp.ones(batch + (1,))]
        for i in range(1, d):
            beta_conc1 = i / 2.0
            beta_conc0 = conc + (d - 1 - i) / 2.0
            y = _jax.random.beta(ks[2 * i], beta_conc1, beta_conc0, batch)
            u = _jax.random.normal(ks[2 * i + 1], batch + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            diag = jnp.sqrt(jnp.clip(1.0 - y, 1e-12, None))[..., None]
            rows.append(jnp.concatenate([w, diag], axis=-1))
        L = jnp.zeros(batch + (d, d))
        for i, r in enumerate(rows):
            L = L.at[..., i, :i + 1].set(r)
        return Tensor(L)

    def log_prob(self, value):
        def _f(conc, L):
            d = self.dim
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            orders = jnp.arange(2, d + 1, dtype=jnp.float32)
            unnorm = ((2.0 * (conc[..., None] - 1.0) + d - orders)
                      * jnp.log(diag)).sum(-1)
            # normalization constant (Stan's lkj_corr_cholesky_log):
            # sum_k [ k/2 log(pi) + log B(conc + (d-1-k)/2, ...) terms ]
            lg = jax.scipy.special.gammaln
            lognorm = jnp.zeros(conc.shape)
            for k in range(1, d):
                lognorm = lognorm + (
                    0.5 * k * jnp.log(jnp.pi)
                    + lg(conc + (d - 1 - k) / 2.0)
                    - lg(conc + (d - 1) / 2.0))
            return unnorm - lognorm
        val = value._data if isinstance(value, Tensor) else _arr(value)
        return apply_op("lkj_log_prob", _f,
                        self._param(self._conc_p, self.concentration),
                        Tensor(val) if not isinstance(value, Tensor)
                        else value)
