"""Probability distributions.

Parity: reference `python/paddle/distribution/` (Distribution base with
sample/rsample/log_prob/entropy/kl_divergence registry; Normal, Uniform,
Categorical, Bernoulli, Beta, Gamma, Dirichlet, Exponential, Geometric,
Gumbel, Laplace, LogNormal, Multinomial, TransformedDistribution).

TPU-native: sampling draws jax PRNG keys from the framework RNG stream
(framework.random.rng_key), so sampling is reproducible under paddle.seed
and traceable under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Gamma", "Dirichlet", "Exponential", "Geometric",
           "Gumbel", "Laplace", "LogNormal", "Multinomial",
           "kl_divergence", "register_kl"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32)


def _key():
    from ..framework.random import rng_key
    return rng_key()


class Distribution:
    """Base. Parity: paddle.distribution.Distribution."""

    @staticmethod
    def _param(tensor_or_none, raw):
        """Prefer the user's original Tensor (keeps the autograd edge for
        reparameterized sampling) over the unwrapped array."""
        return tensor_or_none if tensor_or_none is not None else raw

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op("prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def _extend(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape


class Normal(Distribution):
    """Parameters given as Tensors stay differentiable: log_prob and
    rsample route them through apply_op, so reparameterized-gradient VI
    (d loss/d loc, d loss/d scale) works."""

    def __init__(self, loc, scale, name=None):
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self._scale_p = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def _params(self):
        return (self._param(self._loc_p, self.loc),
                self._param(self._scale_p, self.scale))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    def rsample(self, shape=()):
        z = jax.random.normal(_key(), self._extend(shape), jnp.float32)
        loc, scale = self._params()
        return apply_op("normal_rsample",
                        lambda l, s: l + s * z, loc, scale)

    def sample(self, shape=()):
        # non-reparameterized: detached from loc/scale (reference/torch
        # convention — REINFORCE-style estimators rely on this)
        return Tensor(self.rsample(shape)._data)

    def log_prob(self, value):
        def _f(v, l, s):
            var = s ** 2
            return (-((v - l) ** 2) / (2 * var)
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        loc, scale = self._params()
        return apply_op("normal_log_prob", _f, value, loc, scale)

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            jnp.broadcast_to(self.scale, self._batch_shape))
        return Tensor(e)

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self._low_p = low if isinstance(low, Tensor) else None
        self._high_p = high if isinstance(high, Tensor) else None
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def rsample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape), jnp.float32)
        lo = self._param(self._low_p, self.low)
        hi = self._param(self._high_p, self.high)
        return apply_op("uniform_rsample",
                        lambda lo_, hi_: lo_ + (hi_ - lo_) * u, lo, hi)

    def sample(self, shape=()):
        return Tensor(self.rsample(shape)._data)

    def log_prob(self, value):
        def _f(v):
            inside = (v >= self.low) & (v < self.high)
            lp = -jnp.log(self.high - self.low)
            return jnp.where(inside, lp, -jnp.inf)
        return apply_op("uniform_log_prob", _f, value)

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self._batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _arr(logits)
        else:
            self.logits = jnp.log(jnp.maximum(_arr(probs), 1e-30))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=()):
        out = jax.random.categorical(_key(), self.logits,
                                     shape=tuple(shape) + self._batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        def _f(v):
            logp = jax.nn.log_softmax(self.logits, axis=-1)
            vi = v.astype(jnp.int32)
            b = jnp.broadcast_shapes(logp.shape[:-1], vi.shape)
            logp_b = jnp.broadcast_to(logp, b + logp.shape[-1:])
            vi_b = jnp.broadcast_to(vi, b)
            return jnp.take_along_axis(logp_b, vi_b[..., None],
                                       axis=-1)[..., 0]
        return apply_op("categorical_log_prob", _f, value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-jnp.sum(p * logp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    def sample(self, shape=()):
        u = jax.random.bernoulli(_key(), self.probs_arr,
                                 self._extend(shape))
        return Tensor(u.astype(jnp.float32))

    def log_prob(self, value):
        def _f(v):
            p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op("bernoulli_log_prob", _f, value)

    def entropy(self):
        p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta,
                                      self._extend(shape)))

    def log_prob(self, value):
        def _f(v):
            from jax.scipy.special import betaln
            return ((self.alpha - 1) * jnp.log(v)
                    + (self.beta - 1) * jnp.log1p(-v)
                    - betaln(self.alpha, self.beta))
        return apply_op("beta_log_prob", _f, value)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        g = jax.random.gamma(_key(), self.concentration,
                             self._extend(shape))
        return Tensor(g / self.rate)

    def log_prob(self, value):
        def _f(v):
            from jax.scipy.special import gammaln
            a, b = self.concentration, self.rate
            return (a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                    - gammaln(a))
        return apply_op("gamma_log_prob", _f, value)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(
            _key(), self.concentration,
            tuple(shape) + self._batch_shape))

    def log_prob(self, value):
        def _f(v):
            from jax.scipy.special import gammaln
            a = self.concentration
            return (jnp.sum((a - 1) * jnp.log(v), axis=-1)
                    + gammaln(jnp.sum(a, axis=-1))
                    - jnp.sum(gammaln(a), axis=-1))
        return apply_op("dirichlet_log_prob", _f, value)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        e = jax.random.exponential(_key(), self._extend(shape))
        return Tensor(e / self.rate)

    def log_prob(self, value):
        return apply_op("exp_log_prob",
                        lambda v: jnp.log(self.rate) - self.rate * v, value)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,...} (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape), jnp.float32,
                               minval=1e-7, maxval=1.0)
        k = jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_arr))
        return Tensor(k)

    def log_prob(self, value):
        return apply_op(
            "geom_log_prob",
            lambda v: v * jnp.log1p(-self.probs_arr)
            + jnp.log(self.probs_arr), value)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self._scale_p = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        g = jax.random.gumbel(_key(), self._extend(shape))
        loc = self._param(self._loc_p, self.loc)
        sc = self._param(self._scale_p, self.scale)
        return apply_op("gumbel_rsample", lambda l, s: l + s * g, loc, sc)

    def sample(self, shape=()):
        return Tensor(self.rsample(shape)._data)

    def log_prob(self, value):
        def _f(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply_op("gumbel_log_prob", _f, value)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self._loc_p = loc if isinstance(loc, Tensor) else None
        self._scale_p = scale if isinstance(scale, Tensor) else None
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        l = jax.random.laplace(_key(), self._extend(shape))
        loc = self._param(self._loc_p, self.loc)
        sc = self._param(self._scale_p, self.scale)
        return apply_op("laplace_rsample", lambda lo, s: lo + s * l, loc, sc)

    def sample(self, shape=()):
        return Tensor(self.rsample(shape)._data)

    def log_prob(self, value):
        def _f(v):
            return (-jnp.abs(v - self.loc) / self.scale
                    - jnp.log(2 * self.scale))
        return apply_op("laplace_log_prob", _f, value)

    def entropy(self):
        return Tensor(1 + jnp.log(2 * jnp.broadcast_to(
            self.scale, self._batch_shape)))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        self._normal = Normal(loc, scale)
        super().__init__(self._normal._batch_shape)

    def rsample(self, shape=()):
        return apply_op("exp", jnp.exp, self._normal.rsample(shape))

    def sample(self, shape=()):
        return Tensor(self.rsample(shape)._data)

    def log_prob(self, value):
        def _f(v):
            logv = jnp.log(v)
            var = self.scale ** 2
            return (-((logv - self.loc) ** 2) / (2 * var) - logv
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))
        return apply_op("lognormal_log_prob", _f, value)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape[:-1],
                         self.probs_arr.shape[-1:])

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
        draws = jax.random.categorical(
            _key(), logits,
            shape=(self.total_count,) + tuple(shape) + self._batch_shape)
        k = self.probs_arr.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return Tensor(counts)

    def log_prob(self, value):
        def _f(v):
            from jax.scipy.special import gammaln
            logp = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
            return (gammaln(self.total_count + 1.0)
                    - jnp.sum(gammaln(v + 1.0), axis=-1)
                    + jnp.sum(v * logp, axis=-1))
        return apply_op("multinomial_log_prob", _f, value)


# ---------------------------------------------------------------------------
# KL divergence registry (parity: paddle.distribution.kl_divergence +
# register_kl decorator)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (cp, cq), f in _KL_REGISTRY.items():
            if isinstance(p, cp) and isinstance(q, cq):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence not registered for "
            f"({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    lp = jax.nn.log_softmax(p.logits, axis=-1)
    lq = jax.nn.log_softmax(q.logits, axis=-1)
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    pp = jnp.clip(p.probs_arr, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs_arr, 1e-7, 1 - 1e-7)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    out = jnp.log((q.high - q.low) / (p.high - p.low))
    inside = (q.low <= p.low) & (p.high <= q.high)
    return Tensor(jnp.where(inside, out, jnp.inf))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from jax.scipy.special import betaln, digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t = (betaln(a2, b2) - betaln(a1, b1)
         + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
         + (a2 - a1 + b2 - b1) * digamma(a1 + b1))
    return Tensor(t)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    from jax.scipy.special import digamma, gammaln
    a1, a2 = p.concentration, q.concentration
    s1 = jnp.sum(a1, axis=-1)
    t = (gammaln(s1) - jnp.sum(gammaln(a1), axis=-1)
         - gammaln(jnp.sum(a2, axis=-1)) + jnp.sum(gammaln(a2), axis=-1)
         + jnp.sum((a1 - a2) * (digamma(a1) - digamma(s1)[..., None]),
                   axis=-1))
    return Tensor(t)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    t = (jnp.log(q.scale / p.scale)
         + (p.scale * jnp.exp(-d / p.scale) + d) / q.scale - 1.0)
    return Tensor(t)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    # clip away the p=0/1 boundaries (0*log(0) -> NaN), like _kl_bern_bern
    p1 = jnp.clip(p.probs_arr, 1e-7, 1 - 1e-7)
    p2 = jnp.clip(q.probs_arr, 1e-7, 1 - 1e-7)
    t = (jnp.log(p1 / p2)
         + (1.0 - p1) / p1 * jnp.log((1.0 - p1) / (1.0 - p2)))
    return Tensor(t)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    t = jnp.log(p.rate / q.rate) + q.rate / p.rate - 1.0
    return Tensor(t)


# distribution tail (transforms, heavy-tailed/count, MVN) — extra.py
from .extra import (  # noqa: E402,F401
    Poisson, Cauchy, Chi2, StudentT, Binomial, ContinuousBernoulli,
    MultivariateNormal, ExponentialFamily, Independent,
    TransformedDistribution, Transform, AbsTransform, AffineTransform,
    ChainTransform, ExpTransform, IndependentTransform, PowerTransform,
    ReshapeTransform, SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, LKJCholesky,
)

__all__ += [
    "Poisson", "Cauchy", "Chi2", "StudentT", "Binomial",
    "ContinuousBernoulli", "MultivariateNormal", "ExponentialFamily",
    "Independent", "TransformedDistribution", "Transform", "AbsTransform",
    "AffineTransform", "ChainTransform", "ExpTransform",
    "IndependentTransform", "PowerTransform", "ReshapeTransform",
    "SigmoidTransform", "SoftmaxTransform", "StackTransform",
    "StickBreakingTransform", "TanhTransform", "LKJCholesky",
]


# module-path parity (reference has one file per distribution)
from . import chi2, kl, lkj_cholesky, transform  # noqa: F401,E402
