"""paddle.distribution.transform — module-path parity (reference
distribution/transform.py); implementations live in distribution.extra."""
from . import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform,
    ExpTransform, IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform,
)

__all__ = ["Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SigmoidTransform",
           "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
           "TanhTransform"]
