"""paddle.distribution.lkj_cholesky — module-path parity (reference
distribution/lkj_cholesky.py); the implementation lives in distribution.extra."""
from . import LKJCholesky  # noqa: F401

__all__ = ["LKJCholesky"]
