"""paddle.distribution.chi2 — module-path parity (reference
distribution/chi2.py); the implementation lives in distribution.extra."""
from . import Chi2  # noqa: F401

__all__ = ["Chi2"]
