"""paddle.distribution.kl — module-path parity (reference
distribution/kl.py: kl_divergence + register_kl dispatch)."""
from . import kl_divergence, register_kl  # noqa: F401

__all__ = ["kl_divergence", "register_kl"]
