"""`paddle.tensor` namespace (reference `python/paddle/tensor/`): the
functional tensor API as a module, aliasing the ops layer. Functions are
also monkey-patched onto Tensor (ops/methods.py), matching the reference's
dual module/method surface."""
from .ops import *  # noqa: F401,F403
from .ops import (creation, linalg, logic, manipulation, math,  # noqa: F401
                  random, search)
from .ops.search import top_p_sampling  # noqa: F401
