"""paddle_tpu.vision — datasets, transforms, models, ops.

Parity: reference `python/paddle/vision/`.
"""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
