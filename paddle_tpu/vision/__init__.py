"""paddle_tpu.vision — datasets, transforms, models, ops.

Parity: reference `python/paddle/vision/`.
"""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """Parity: paddle.vision.set_image_backend ('pil' or 'cv2'; this
    build ships PIL)."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (parity: paddle.vision.image_load): the 'pil'
    backend returns a PIL Image, 'cv2' an HWC BGR ndarray (decoded via
    PIL here — OpenCV isn't shipped, but the return-type contract
    holds)."""
    import numpy as np
    from PIL import Image
    b = backend or _image_backend
    img = Image.open(path)
    if b == "cv2":
        arr = np.asarray(img.convert("RGB"))
        return arr[:, :, ::-1].copy()   # BGR like cv2.imread
    return img
