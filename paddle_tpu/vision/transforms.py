"""Vision transforms (numpy/host-side preprocessing).

Parity: reference `python/paddle/vision/transforms/transforms.py` — the
common subset used by the dataset pipelines.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "RandomResizedCrop", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "Grayscale", "RandomRotation", "RandomAffine",
           "RandomPerspective", "RandomErasing",
           "to_tensor", "normalize", "resize", "hflip",
           "vflip", "crop", "center_crop", "pad", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _chw(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def to_tensor(pic, data_format="CHW"):
    a = _chw(pic).astype(np.float32)
    if a.max() > 1.5:
        a = a / 255.0
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return a


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (a - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    a = _chw(img)
    h, w = a.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    ys = np.clip(ys, 0, h - 1)
    xs = np.clip(xs, 0, w - 1)
    if interpolation == "nearest":
        out = a[np.round(ys).astype(int)[:, None], np.round(xs).astype(int)[None, :]]
    else:
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y0][:, x1] * (1 - wy) * wx +
               a[y1][:, x0] * wy * (1 - wx) + a[y1][:, x1] * wy * wx)
        out = out.astype(a.dtype) if np.issubdtype(a.dtype, np.floating) else \
            np.clip(out, 0, 255).astype(a.dtype)
    return out


def hflip(img):
    return np.asarray(img)[:, ::-1]


def vflip(img):
    return np.asarray(img)[::-1]


def crop(img, top, left, height, width):
    return _chw(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = _chw(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = a.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(a, i, j, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _chw(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(a, ((t, b), (l, r), (0, 0)), mode=mode, **kw)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        a = _chw(img)
        if self.padding is not None:
            a = pad(a, self.padding, self.fill, self.padding_mode)
        h, w = a.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return a
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return crop(a, i, j, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.transpose(_chw(img), self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        a = _chw(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return resize(crop(a, i, j, th, tw), self.size, self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size, self.interpolation)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        a = np.asarray(img).astype(np.float32) * factor
        return np.clip(a, 0, 255).astype(np.asarray(img).dtype)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, factor)


def adjust_brightness(img, factor):
    a = np.asarray(img, np.float32)
    hi = 255.0 if a.max() > 1.5 else 1.0
    return np.clip(a * factor, 0, hi).astype(np.asarray(img).dtype)


def adjust_contrast(img, factor):
    a = np.asarray(img, np.float32)
    hi = 255.0 if a.max() > 1.5 else 1.0
    mean = a.mean()
    return np.clip((a - mean) * factor + mean, 0, hi).astype(
        np.asarray(img).dtype)


def adjust_saturation(img, factor):
    a = _chw(np.asarray(img, np.float32))
    gray = a @ np.array([0.299, 0.587, 0.114], np.float32) \
        if a.shape[-1] == 3 else a[..., 0]
    hi = 255.0 if a.max() > 1.5 else 1.0
    out = a * factor + gray[..., None] * (1 - factor)
    return np.clip(out, 0, hi).astype(np.asarray(img).dtype)


def adjust_hue(img, factor):
    """factor in [-0.5, 0.5]: rotate the hue channel in HSV space."""
    a = _chw(np.asarray(img, np.float32))
    scale = 255.0 if a.max() > 1.5 else 1.0
    x = a / scale
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b) / diff % 6)[m]
    m = mx == g
    h[m] = ((b - r) / diff + 2)[m]
    m = mx == b
    h[m] = ((r - g) / diff + 4)[m]
    h = (h / 6 + factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1)
    return (out * scale).astype(np.asarray(img).dtype)


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter:
    """Parity: transforms.ColorJitter — random brightness/contrast/
    saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.b, self.c, self.s, self.h = brightness, contrast, saturation, hue

    def __call__(self, img):
        ops = []
        if self.b:
            f = random.uniform(max(0, 1 - self.b), 1 + self.b)
            ops.append(lambda im: adjust_brightness(im, f))
        if self.c:
            g = random.uniform(max(0, 1 - self.c), 1 + self.c)
            ops.append(lambda im: adjust_contrast(im, g))
        if self.s:
            h = random.uniform(max(0, 1 - self.s), 1 + self.s)
            ops.append(lambda im: adjust_saturation(im, h))
        if self.h:
            k = random.uniform(-self.h, self.h)
            ops.append(lambda im: adjust_hue(im, k))
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        a = _chw(np.asarray(img, np.float32))
        g = a @ np.array([0.299, 0.587, 0.114], np.float32) \
            if a.shape[-1] == 3 else a[..., 0]
        out = np.repeat(g[..., None], self.n, axis=-1)
        return out.astype(np.asarray(img).dtype)


def _grid_sample_nearest(a, sx, sy, fill=0):
    """Nearest-neighbor gather at float source coordinates; out-of-range
    positions take `fill`."""
    h, w = a.shape[:2]
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(a, fill)
    out[valid] = a[syi[valid], sxi[valid]]
    return out


def _affine_grid_sample(a, mat, fill=0):
    """Inverse-warp HWC image by 2x3 affine matrix (nearest sampling)."""
    h, w = a.shape[:2]
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    xs = xx - cx
    ys = yy - cy
    sx = mat[0, 0] * xs + mat[0, 1] * ys + mat[0, 2] + cx
    sy = mat[1, 0] * xs + mat[1, 1] * ys + mat[1, 2] + cy
    return _grid_sample_nearest(a, sx, sy, fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        if expand or center is not None:
            raise NotImplementedError(
                "RandomRotation expand/center not supported")
        self.degrees, self.fill = degrees, fill

    def __call__(self, img):
        a = _chw(np.asarray(img))
        ang = np.deg2rad(random.uniform(*self.degrees))
        c, s = np.cos(ang), np.sin(ang)
        mat = np.array([[c, -s, 0.0], [s, c, 0.0]], np.float32)
        return _affine_grid_sample(a, mat, self.fill)


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        if center is not None:
            raise NotImplementedError("RandomAffine center not supported")
        if isinstance(shear, numbers.Number):
            shear = (-shear, shear)
        self.degrees, self.translate = degrees, translate
        self.scale, self.shear, self.fill = scale, shear, fill

    def __call__(self, img):
        a = _chw(np.asarray(img))
        h, w = a.shape[:2]
        ang = np.deg2rad(random.uniform(*self.degrees))
        sc = random.uniform(*self.scale) if self.scale else 1.0
        tx = ty = 0.0
        if self.translate:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        shx = np.deg2rad(random.uniform(*self.shear)) if self.shear else 0.0
        c, s = np.cos(ang), np.sin(ang)
        rot = np.array([[c, -s], [s, c]], np.float32)
        sh = np.array([[1.0, np.tan(shx)], [0.0, 1.0]], np.float32)
        lin = (rot @ sh) / sc
        mat = np.array([[lin[0, 0], lin[0, 1], -tx],
                        [lin[1, 0], lin[1, 1], -ty]], np.float32)
        return _affine_grid_sample(a, mat, self.fill)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob, self.d, self.fill = prob, distortion_scale, fill

    def __call__(self, img):
        if random.random() >= self.prob:
            return img
        a = _chw(np.asarray(img))
        h, w = a.shape[:2]
        d = self.d
        # jitter the 4 corners and fit the projective map (8 dof)
        src = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                       np.float32)
        jit = np.array([[random.uniform(0, d * w / 2),
                         random.uniform(0, d * h / 2)] for _ in range(4)],
                       np.float32)
        sign = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]], np.float32)
        dst = src + jit * sign
        A = []
        for (x, y), (u, v) in zip(dst, src):
            A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
            A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bvec = src.reshape(-1)
        coef = np.linalg.lstsq(np.array(A, np.float32), bvec, rcond=None)[0]
        M = np.append(coef, 1.0).reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        den = M[2, 0] * xx + M[2, 1] * yy + M[2, 2]
        sx = (M[0, 0] * xx + M[0, 1] * yy + M[0, 2]) / den
        sy = (M[1, 0] * xx + M[1, 1] * yy + M[1, 2]) / den
        return _grid_sample_nearest(a, sx, sy, self.fill)


class RandomErasing:
    """Parity: transforms.RandomErasing (CHW tensors or HWC arrays)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob, self.scale, self.ratio, self.value = \
            prob, scale, ratio, value

    def __call__(self, img):
        a = np.array(img, copy=True)
        if random.random() >= self.prob:
            return a
        chw = a.ndim == 3 and a.shape[0] in (1, 3)
        h, w = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ratio = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ratio)))
            ew = int(round(np.sqrt(target / ratio)))
            if eh < h and ew < w:
                y = random.randint(0, h - eh)
                x = random.randint(0, w - ew)
                if chw:
                    a[:, y:y + eh, x:x + ew] = self.value
                else:
                    a[y:y + eh, x:x + ew] = self.value
                break
        return a


class BaseTransform:
    """Parity: transforms.BaseTransform — the base class of the paired
    image/label transform protocol (keys select which inputs the
    transform touches; subclasses implement _apply_image et al.)."""

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        return image

    def _apply_boxes(self, boxes):
        return boxes

    def _apply_mask(self, mask):
        return mask

    def __call__(self, inputs):
        single = not isinstance(inputs, (list, tuple))
        items = (inputs,) if single else tuple(inputs)
        self.params = self._get_params(items)
        out = []
        for key, item in zip(self.keys, items):
            base = key.rstrip("0123456789")
            fn = getattr(self, f"_apply_{base}", None)
            out.append(fn(item) if fn is not None else item)
        out += list(items[len(self.keys):])
        return out[0] if single else tuple(out)


# ------------------------- functional forms (transforms.functional) ----
def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Parity: transforms.rotate — fixed-angle rotation about the image
    center (nearest sampling)."""
    if expand or center is not None:
        raise NotImplementedError("rotate expand/center not supported")
    a = _chw(np.asarray(img))
    ang = np.deg2rad(angle)
    c, s = np.cos(ang), np.sin(ang)
    mat = np.array([[c, -s, 0.0], [s, c, 0.0]], np.float32)
    return _affine_grid_sample(a, mat, fill)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Parity: transforms.affine — deterministic affine warp."""
    if center is not None:
        raise NotImplementedError("affine center not supported")
    a = _chw(np.asarray(img))
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    ang = np.deg2rad(angle)
    shx = np.deg2rad(shear[0])
    c, s = np.cos(ang), np.sin(ang)
    rot = np.array([[c, -s], [s, c]], np.float32)
    sh = np.array([[1.0, np.tan(shx)], [0.0, 1.0]], np.float32)
    lin = (rot @ sh) / float(scale)
    mat = np.array([[lin[0, 0], lin[0, 1], -translate[0]],
                    [lin[1, 0], lin[1, 1], -translate[1]]], np.float32)
    return _affine_grid_sample(a, mat, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Parity: transforms.perspective — warp mapping endpoints back onto
    startpoints (8-dof projective fit, nearest sampling)."""
    a = _chw(np.asarray(img))
    h, w = a.shape[:2]
    src = np.asarray(startpoints, np.float32)
    dst = np.asarray(endpoints, np.float32)
    A = []
    for (x, y), (u, v) in zip(dst, src):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    coef = np.linalg.lstsq(np.array(A, np.float32), src.reshape(-1),
                           rcond=None)[0]
    M = np.append(coef, 1.0).reshape(3, 3)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = M[2, 0] * xx + M[2, 1] * yy + M[2, 2]
    sx = (M[0, 0] * xx + M[0, 1] * yy + M[0, 2]) / den
    sy = (M[1, 0] * xx + M[1, 1] * yy + M[1, 2]) / den
    return _grid_sample_nearest(a, sx, sy, fill)


def to_grayscale(img, num_output_channels=1):
    """Parity: transforms.to_grayscale."""
    return Grayscale(num_output_channels)(img)


def erase(img, i, j, h, w, v, inplace=False):
    """Parity: transforms.erase — fill the (i, j, h, w) box with v.
    Accepts Tensors (CHW) or numpy arrays (CHW/HWC)."""
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        a = img._data
        patch = jnp.broadcast_to(jnp.asarray(v, a.dtype),
                                 a[..., i:i + h, j:j + w].shape)
        out = a.at[..., i:i + h, j:j + w].set(patch)
        if inplace:
            img._data = out
            return img
        return Tensor(out)
    a = np.array(img, copy=not inplace)
    chw = a.ndim == 3 and a.shape[0] in (1, 3)
    if chw:
        a[:, i:i + h, j:j + w] = v
    else:
        a[i:i + h, j:j + w] = v
    return a


__all__ += ["BaseTransform", "affine", "rotate", "perspective",
            "to_grayscale", "erase"]
