"""Vision transforms (numpy/host-side preprocessing).

Parity: reference `python/paddle/vision/transforms/transforms.py` — the
common subset used by the dataset pipelines.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "RandomResizedCrop", "BrightnessTransform",
           "ContrastTransform", "to_tensor", "normalize", "resize", "hflip",
           "vflip", "crop", "center_crop", "pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _chw(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def to_tensor(pic, data_format="CHW"):
    a = _chw(pic).astype(np.float32)
    if a.max() > 1.5:
        a = a / 255.0
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return a


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (a - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    a = _chw(img)
    h, w = a.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    ys = np.clip(ys, 0, h - 1)
    xs = np.clip(xs, 0, w - 1)
    if interpolation == "nearest":
        out = a[np.round(ys).astype(int)[:, None], np.round(xs).astype(int)[None, :]]
    else:
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y0][:, x1] * (1 - wy) * wx +
               a[y1][:, x0] * wy * (1 - wx) + a[y1][:, x1] * wy * wx)
        out = out.astype(a.dtype) if np.issubdtype(a.dtype, np.floating) else \
            np.clip(out, 0, 255).astype(a.dtype)
    return out


def hflip(img):
    return np.asarray(img)[:, ::-1]


def vflip(img):
    return np.asarray(img)[::-1]


def crop(img, top, left, height, width):
    return _chw(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = _chw(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = a.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(a, i, j, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _chw(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(a, ((t, b), (l, r), (0, 0)), mode=mode, **kw)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        a = _chw(img)
        if self.padding is not None:
            a = pad(a, self.padding, self.fill, self.padding_mode)
        h, w = a.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return a
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return crop(a, i, j, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.transpose(_chw(img), self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        a = _chw(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return resize(crop(a, i, j, th, tw), self.size, self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size, self.interpolation)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        a = np.asarray(img).astype(np.float32) * factor
        return np.clip(a, 0, 255).astype(np.asarray(img).dtype)


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        a = np.asarray(img).astype(np.float32)
        mean = a.mean()
        out = (a - mean) * factor + mean
        return np.clip(out, 0, 255).astype(np.asarray(img).dtype)
