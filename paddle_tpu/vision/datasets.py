"""Vision datasets. Parity: reference python/paddle/vision/datasets/
(MNIST, Cifar10/100, FashionMNIST...). Zero-egress environment: datasets
load from local files when present, else generate deterministic synthetic
data (shape/dtype-faithful) so training pipelines run end-to-end.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageDataset"]

_DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                               "~/.cache/paddle_tpu/datasets"))


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification dataset."""

    def __init__(self, num_samples, image_shape, num_classes, transform=None,
                 seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randint(0, 256, self.image_shape).astype(np.uint8)
        label = np.asarray(idx % self.num_classes, np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """MNIST from local idx files if available, else synthetic fallback."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        self.mode = mode
        base = os.path.join(_DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        img_f = image_path or os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lab_f = label_path or os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_f) and os.path.exists(lab_f):
            with gzip.open(img_f, "rb") as f:
                data = np.frombuffer(f.read(), np.uint8, offset=16)
            self.images = data.reshape(-1, 28, 28)
            with gzip.open(lab_f, "rb") as f:
                self.labels = np.frombuffer(f.read(), np.uint8, offset=8).astype(np.int64)
        else:
            n = 60000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (28, 28), 10)
            self.images = None
            self.labels = None
            self._n = n

    def __getitem__(self, idx):
        if self.images is None:
            img, label = self._fake[idx]
        else:
            img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self._n if self.images is None else len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from local python-pickle tarball if available, else synthetic."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(_DATA_HOME, "cifar-10-python.tar.gz")
        self.num_classes = 10
        if os.path.exists(data_file):
            self.data, self.labels = self._load_tar(data_file, mode)
        else:
            n = 50000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (32, 32, 3), self.num_classes)
            self.data = None
            self._n = n

    def _load_tar(self, path, mode):
        imgs, labels = [], []
        names = [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" \
            else ["test_batch"]
        key = b"labels" if self.num_classes == 10 else b"fine_labels"
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if any(member.name.endswith(n) for n in names):
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                    labels.extend(d[key])
        return np.concatenate(imgs), np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        if self.data is None:
            img, label = self._fake[idx]
        else:
            img, label = self.data[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self._n if self.data is None else len(self.data)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(_DATA_HOME, "cifar-100-python.tar.gz")
        self.num_classes = 100
        if os.path.exists(data_file):
            self.data, self.labels = self._load_tar(data_file, mode)
        else:
            n = 50000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (32, 32, 3), self.num_classes)
            self.data = None
            self._n = n


def _load_image(path, backend=None):
    """Image file -> HWC uint8 numpy (PIL backend, 'cv2' unavailable)."""
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


class DatasetFolder(Dataset):
    """Parity: vision.datasets.DatasetFolder — `root/<class>/<file>`
    layout; classes are the sorted subdirectory names."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        classes = sorted(e for e in os.listdir(root)
                         if os.path.isdir(os.path.join(root, e)))
        if not classes:
            raise RuntimeError(f"DatasetFolder: no class folders in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(dirpath, f)
                    ok = is_valid_file(path) if is_valid_file else \
                        f.lower().endswith(exts)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"DatasetFolder: no valid files under {root} "
                f"(extensions {exts})")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Parity: vision.datasets.ImageFolder — a flat (or nested) folder of
    images, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                path = os.path.join(dirpath, f)
                ok = is_valid_file(path) if is_valid_file else \
                    f.lower().endswith(exts)
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"ImageFolder: no valid files under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Parity: vision.datasets.Flowers (102 Category Flowers). Reads the
    standard local artifacts (102flowers.tgz extracted + setid.mat +
    imagelabels.mat) under data_file; synthetic fallback when absent
    (zero-egress build — same stance as MNIST above)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        base = data_file or os.path.join(_DATA_HOME, "flowers")
        jpg_dir = os.path.join(base, "jpg")
        labels_f = label_file or os.path.join(base, "imagelabels.mat")
        setid_f = setid_file or os.path.join(base, "setid.mat")
        if os.path.isdir(jpg_dir) and os.path.exists(labels_f) \
                and os.path.exists(setid_f):
            from scipy.io import loadmat
            labels = loadmat(labels_f)["labels"].reshape(-1)
            key = {"train": "trnid", "valid": "valid",
                   "test": "tstid"}[mode]
            ids = loadmat(setid_f)[key].reshape(-1)
            self._items = [
                (os.path.join(jpg_dir, f"image_{i:05d}.jpg"),
                 int(labels[i - 1]) - 1) for i in ids]
            self._fake = None
        else:
            n = {"train": 1020, "valid": 1020, "test": 6149}[mode]
            self._fake = FakeImageDataset(n, (64, 64, 3), 102)
            self._items = None
            self._n = n

    def __getitem__(self, idx):
        if self._fake is not None:
            img, label = self._fake[idx]
        else:
            path, label = self._items[idx]
            img = _load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self._n if self._items is None else len(self._items)


class VOC2012(Dataset):
    """Parity: vision.datasets.VOC2012 (segmentation pairs). Reads a
    local VOCdevkit/VOC2012 tree; synthetic (image, mask) fallback when
    absent."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        base = data_file or os.path.join(_DATA_HOME, "VOCdevkit", "VOC2012")
        lst = os.path.join(base, "ImageSets", "Segmentation",
                           {"train": "train", "valid": "val",
                            "test": "val"}[mode] + ".txt")
        if os.path.exists(lst):
            names = [l.strip() for l in open(lst) if l.strip()]
            self._items = [
                (os.path.join(base, "JPEGImages", n + ".jpg"),
                 os.path.join(base, "SegmentationClass", n + ".png"))
                for n in names]
        else:
            self._items = None
            self._n = 32
            rng = np.random.RandomState(0)
            self._imgs = rng.randint(0, 255, (self._n, 64, 64, 3),
                                     np.uint8)
            self._masks = rng.randint(0, 21, (self._n, 64, 64), np.uint8)

    def __getitem__(self, idx):
        if self._items is None:
            img, mask = self._imgs[idx], self._masks[idx]
        else:
            ip, mp = self._items[idx]
            from PIL import Image
            img = _load_image(ip)
            with Image.open(mp) as m:
                mask = np.asarray(m)
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self._n if self._items is None else len(self._items)


__all__ += ["DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]
