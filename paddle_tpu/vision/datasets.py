"""Vision datasets. Parity: reference python/paddle/vision/datasets/
(MNIST, Cifar10/100, FashionMNIST...). Zero-egress environment: datasets
load from local files when present, else generate deterministic synthetic
data (shape/dtype-faithful) so training pipelines run end-to-end.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageDataset"]

_DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                               "~/.cache/paddle_tpu/datasets"))


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification dataset."""

    def __init__(self, num_samples, image_shape, num_classes, transform=None,
                 seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randint(0, 256, self.image_shape).astype(np.uint8)
        label = np.asarray(idx % self.num_classes, np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """MNIST from local idx files if available, else synthetic fallback."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.transform = transform
        self.mode = mode
        base = os.path.join(_DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        img_f = image_path or os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lab_f = label_path or os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_f) and os.path.exists(lab_f):
            with gzip.open(img_f, "rb") as f:
                data = np.frombuffer(f.read(), np.uint8, offset=16)
            self.images = data.reshape(-1, 28, 28)
            with gzip.open(lab_f, "rb") as f:
                self.labels = np.frombuffer(f.read(), np.uint8, offset=8).astype(np.int64)
        else:
            n = 60000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (28, 28), 10)
            self.images = None
            self.labels = None
            self._n = n

    def __getitem__(self, idx):
        if self.images is None:
            img, label = self._fake[idx]
        else:
            img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self._n if self.images is None else len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from local python-pickle tarball if available, else synthetic."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(_DATA_HOME, "cifar-10-python.tar.gz")
        self.num_classes = 10
        if os.path.exists(data_file):
            self.data, self.labels = self._load_tar(data_file, mode)
        else:
            n = 50000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (32, 32, 3), self.num_classes)
            self.data = None
            self._n = n

    def _load_tar(self, path, mode):
        imgs, labels = [], []
        names = [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" \
            else ["test_batch"]
        key = b"labels" if self.num_classes == 10 else b"fine_labels"
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if any(member.name.endswith(n) for n in names):
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                    labels.extend(d[key])
        return np.concatenate(imgs), np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        if self.data is None:
            img, label = self._fake[idx]
        else:
            img, label = self.data[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self._n if self.data is None else len(self.data)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(_DATA_HOME, "cifar-100-python.tar.gz")
        self.num_classes = 100
        if os.path.exists(data_file):
            self.data, self.labels = self._load_tar(data_file, mode)
        else:
            n = 50000 if mode == "train" else 10000
            self._fake = FakeImageDataset(n, (32, 32, 3), self.num_classes)
            self.data = None
            self._n = n
