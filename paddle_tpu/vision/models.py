"""Vision model zoo.

Parity: reference `python/paddle/vision/models/` — resnet.py (+wide/
resnext variants), vgg.py, alexnet.py, mobilenetv1/v2/v3.py,
squeezenet.py, shufflenetv2.py, densenet.py, googlenet.py, lenet.py.
"""
from __future__ import annotations

from .. import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2",
           "resnext50_32x4d", "resnext101_64x4d", "LeNet", "VGG", "vgg11",
           "vgg13", "vgg16", "vgg19", "AlexNet", "alexnet", "MobileNetV1",
           "mobilenet_v1", "MobileNetV2", "mobilenet_v2", "MobileNetV3",
           "mobilenet_v3_small", "mobilenet_v3_large", "SqueezeNet",
           "squeezenet1_1", "ShuffleNetV2", "shufflenet_v2_x1_0",
           "DenseNet", "densenet121", "GoogLeNet", "googlenet"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """Parity: python/paddle/vision/models/resnet.py ResNet."""

    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups, base_width=self.base_width))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        from ..ops.manipulation import flatten
        x = flatten(x, 1)
        return self.fc(x)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        from ..ops.manipulation import flatten
        x = flatten(x, 1)
        return self.classifier(x)


def _vgg_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(inp, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                             groups=hidden, bias_attr=False),
                   nn.BatchNorm2D(hidden), nn.ReLU6(),
                   nn.Conv2D(hidden, oup, 1, bias_attr=False),
                   nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        if self.use_res:
            return x + self.conv(x)
        return self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        input_channel = int(32 * scale)
        features = [nn.Conv2D(3, input_channel, 3, stride=2, padding=1,
                              bias_attr=False),
                    nn.BatchNorm2D(input_channel), nn.ReLU6()]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(_InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        last = int(1280 * max(1.0, scale))
        features += [nn.Conv2D(input_channel, last, 1, bias_attr=False),
                     nn.BatchNorm2D(last), nn.ReLU6()]
        self.features = nn.Sequential(*features)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class AlexNet(nn.Layer):
    """Parity: python/paddle/vision/models/alexnet.py."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        from ..ops.manipulation import flatten
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
           512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
           512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=128, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=32, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=64, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=32, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=64, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, width=4, groups=32, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, width=4, groups=64, **kwargs)


class MobileNetV1(nn.Layer):
    """Depthwise-separable stack. Parity: vision/models/mobilenetv1.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        def c(ch):
            return max(int(ch * scale), 8)

        def dw_sep(inp, oup, stride):
            return nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp), nn.ReLU(),
                nn.Conv2D(inp, oup, 1, bias_attr=False),
                nn.BatchNorm2D(oup), nn.ReLU())

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        feats = [nn.Conv2D(3, c(32), 3, stride=2, padding=1, bias_attr=False),
                 nn.BatchNorm2D(c(32)), nn.ReLU()]
        for inp, oup, s in cfg:
            feats.append(dw_sep(c(inp), c(oup), s))
        self.features = nn.Sequential(*feats)
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, inp, hidden, oup, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        Act = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if hidden != inp:
            layers += [nn.Conv2D(inp, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), Act()]
        layers += [nn.Conv2D(hidden, hidden, k, stride=stride,
                             padding=k // 2, groups=hidden, bias_attr=False),
                   nn.BatchNorm2D(hidden), Act()]
        if use_se:
            layers.append(_SqueezeExcite(hidden, max(hidden // 4, 8)))
        layers += [nn.Conv2D(hidden, oup, 1, bias_attr=False),
                   nn.BatchNorm2D(oup)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(nn.Layer):
    """Parity: vision/models/mobilenetv3.py (small/large configs)."""

    CFG_LARGE = [
        (16, 16, 16, 3, 1, False, "relu"),
        (16, 64, 24, 3, 2, False, "relu"),
        (24, 72, 24, 3, 1, False, "relu"),
        (24, 72, 40, 5, 2, True, "relu"),
        (40, 120, 40, 5, 1, True, "relu"),
        (40, 120, 40, 5, 1, True, "relu"),
        (40, 240, 80, 3, 2, False, "hardswish"),
        (80, 200, 80, 3, 1, False, "hardswish"),
        (80, 184, 80, 3, 1, False, "hardswish"),
        (80, 184, 80, 3, 1, False, "hardswish"),
        (80, 480, 112, 3, 1, True, "hardswish"),
        (112, 672, 112, 3, 1, True, "hardswish"),
        (112, 672, 160, 5, 2, True, "hardswish"),
        (160, 960, 160, 5, 1, True, "hardswish"),
        (160, 960, 160, 5, 1, True, "hardswish"),
    ]
    CFG_SMALL = [
        (16, 16, 16, 3, 2, True, "relu"),
        (16, 72, 24, 3, 2, False, "relu"),
        (24, 88, 24, 3, 1, False, "relu"),
        (24, 96, 40, 5, 2, True, "hardswish"),
        (40, 240, 40, 5, 1, True, "hardswish"),
        (40, 240, 40, 5, 1, True, "hardswish"),
        (40, 120, 48, 5, 1, True, "hardswish"),
        (48, 144, 48, 5, 1, True, "hardswish"),
        (48, 288, 96, 5, 2, True, "hardswish"),
        (96, 576, 96, 5, 1, True, "hardswish"),
        (96, 576, 96, 5, 1, True, "hardswish"),
    ]

    def __init__(self, config="large", scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = self.CFG_LARGE if config == "large" else self.CFG_SMALL
        last_exp = 960 if config == "large" else 576
        def c(ch):
            return max(int(ch * scale), 8)
        feats = [nn.Conv2D(3, c(16), 3, stride=2, padding=1, bias_attr=False),
                 nn.BatchNorm2D(c(16)), nn.Hardswish()]
        for inp, hid, oup, k, s, se, act in cfg:
            feats.append(_MBV3Block(c(inp), c(hid), c(oup), k, s, se, act))
        feats += [nn.Conv2D(c(cfg[-1][2]), c(last_exp), 1, bias_attr=False),
                  nn.BatchNorm2D(c(last_exp)), nn.Hardswish()]
        self.features = nn.Sequential(*feats)
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), 1280), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    """Parity: vision.models.MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__("large", scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    """Parity: vision.models.MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__("small", scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


class SqueezeNet(nn.Layer):
    """Parity: vision/models/squeezenet.py (v1.1)."""

    class Fire(nn.Layer):
        def __init__(self, inp, squeeze, e1, e3):
            super().__init__()
            self.squeeze = nn.Sequential(nn.Conv2D(inp, squeeze, 1),
                                         nn.ReLU())
            self.e1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
            self.e3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                    nn.ReLU())

        def forward(self, x):
            from ..ops.manipulation import concat
            s = self.squeeze(x)
            return concat([self.e1(s), self.e3(s)], axis=1)

    def __init__(self, num_classes=1000, version="1.1"):
        super().__init__()
        F = SqueezeNet.Fire
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                F(96, 16, 64, 64), F(128, 16, 64, 64),
                F(128, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                F(256, 32, 128, 128), F(256, 48, 192, 192),
                F(384, 48, 192, 192), F(384, 64, 256, 256),
                nn.MaxPool2D(3, 2),
                F(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                F(64, 16, 64, 64), F(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                F(128, 32, 128, 128), F(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                F(256, 48, 192, 192), F(384, 48, 192, 192),
                F(384, 64, 256, 256), F(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        from ..ops.manipulation import flatten
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)


class _ShuffleUnit(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        Act = nn.Swish if act == "swish" else nn.ReLU
        self.stride = stride
        branch = oup // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=2, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), Act())
            in2 = inp
        else:
            self.branch1 = None
            in2 = inp // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), Act(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), Act())
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        from ..ops.manipulation import concat, split
        if self.stride == 2:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    """Parity: vision/models/shufflenetv2.py (x1.0)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 act="relu"):
        super().__init__()
        stage_out = {0.25: [24, 48, 96, 512], 0.33: [32, 64, 128, 512],
                     0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024],
                     2.0: [244, 488, 976, 2048]}[scale]
        Act = nn.Swish if act == "swish" else nn.ReLU
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), Act())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        inp = 24
        stages = []
        for i, reps in enumerate([4, 8, 4]):
            oup = stage_out[i]
            units = [_ShuffleUnit(inp, oup, 2, act)]
            for _ in range(reps - 1):
                units.append(_ShuffleUnit(oup, oup, 1, act))
            stages.append(nn.Sequential(*units))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, stage_out[3], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[3]), Act())
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)


class _DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(inp), nn.ReLU(),
            nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    """Parity: vision/models/densenet.py (121 config by default)."""

    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        block_cfg = {121: [6, 12, 24, 16], 161: [6, 12, 36, 24],
                     169: [6, 12, 32, 32], 201: [6, 12, 48, 32],
                     264: [6, 12, 64, 48]}[layers]
        ch = 2 * growth_rate
        feats = [nn.Conv2D(3, ch, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(ch), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1)]
        for i, reps in enumerate(block_cfg):
            for _ in range(reps):
                feats.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if i != len(block_cfg) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, growth_rate=48, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)


class _Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(inp, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(inp, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(inp, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(inp, pp, 1), nn.ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    """Parity: vision/models/googlenet.py (aux heads omitted in eval
    parity; the reference also drops them at inference)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.blocks = nn.Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, 2, padding=1),
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, 2, padding=1),
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.head = nn.Sequential(nn.Dropout(0.2),
                                      nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.head(flatten(x, 1))
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


class _ConvBN(nn.Layer):
    def __init__(self, inp, oup, k, stride=1, padding=0):
        super().__init__()
        self.fn = nn.Sequential(
            nn.Conv2D(inp, oup, k, stride=stride, padding=padding,
                      bias_attr=False),
            nn.BatchNorm2D(oup), nn.ReLU())

    def forward(self, x):
        return self.fn(x)


class _InceptionA(nn.Layer):
    def __init__(self, inp, pool_ch):
        super().__init__()
        self.b1 = _ConvBN(inp, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(inp, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(inp, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBN(inp, pool_ch, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class _InceptionB(nn.Layer):  # grid reduction 35 -> 17
    def __init__(self, inp):
        super().__init__()
        self.b3 = _ConvBN(inp, 384, 3, stride=2)
        self.b33 = nn.Sequential(_ConvBN(inp, 64, 1),
                                 _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.b33(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):  # factorized 7x7
    def __init__(self, inp, ch7):
        super().__init__()
        self.b1 = _ConvBN(inp, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(inp, ch7, 1),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, 192, (7, 1), padding=(3, 0)))
        self.b77 = nn.Sequential(
            _ConvBN(inp, ch7, 1),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)],
                      axis=1)


class _InceptionD(nn.Layer):  # grid reduction 17 -> 8
    def __init__(self, inp):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(inp, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(inp, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):  # expanded-filter-bank output blocks
    def __init__(self, inp):
        super().__init__()
        self.b1 = _ConvBN(inp, 320, 1)
        self.b3_stem = _ConvBN(inp, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b33_stem = nn.Sequential(_ConvBN(inp, 448, 1),
                                      _ConvBN(448, 384, 3, padding=1))
        self.b33_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b33_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        s = self.b3_stem(x)
        t = self.b33_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], axis=1),
                       concat([self.b33_a(t), self.b33_b(t)], axis=1),
                       self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Parity: vision/models/inceptionv3.py (Szegedy et al. 2015; the
    standard A/B/C/D/E block stack over a 299x299 stem; aux head omitted
    like the reference at inference)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.with_pool, self.num_classes = with_pool, num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.head = nn.Sequential(nn.Dropout(0.5),
                                      nn.Linear(2048, num_classes))

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.head(flatten(x, 1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


__all__ += ["resnext50_64x4d", "resnext101_32x4d", "resnext152_32x4d",
            "resnext152_64x4d", "MobileNetV3Small", "MobileNetV3Large",
            "densenet161", "densenet169", "densenet201", "densenet264",
            "InceptionV3", "inception_v3", "squeezenet1_0",
            "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
            "shufflenet_v2_x0_5", "shufflenet_v2_x1_5",
            "shufflenet_v2_x2_0", "shufflenet_v2_swish"]
