"""Vision ops: nms, roi_align, box utils.

Parity: reference `python/paddle/vision/ops.py`: nms, roi_align,
box_coder-adjacent utilities, deform_conv2d (gather-based bilinear
sampling), distribute_fpn_proposals, generate_proposals, matrix_nms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op
from ..nn.layer.layers import Layer as _Layer

__all__ = ["nms", "roi_align", "box_area", "box_iou", "psroi_pool",
           "roi_pool", "deform_conv2d", "DeformConv2D", "box_coder",
           "prior_box", "yolo_box", "yolo_loss", "yolov3_loss",
           "matrix_nms", "distribute_fpn_proposals", "generate_proposals",
           "RoIPool", "RoIAlign", "PSRoIPool", "read_file", "decode_jpeg"]


def box_area(boxes):
    return apply_op("box_area",
                    lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes)


def box_iou(boxes1, boxes2):
    def _f(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply_op("box_iou", _f, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host-side; dynamic output shape). Parity: vision/ops.py nms."""
    b = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._data) if scores is not None else None
    order = np.argsort(-s) if s is not None else np.arange(len(b))
    if category_idxs is not None:
        cats = np.asarray(category_idxs._data if isinstance(category_idxs, Tensor)
                          else category_idxs)
    else:
        cats = np.zeros(len(b), np.int64)
    keep = []
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if j == i or suppressed[j] or cats[j] != cats[i]:
                continue
            xx1 = max(b[i, 0], b[j, 0])
            yy1 = max(b[i, 1], b[j, 1])
            xx2 = min(b[i, 2], b[j, 2])
            yy2 = min(b[i, 3], b[j, 3])
            inter = max(0.0, xx2 - xx1) * max(0.0, yy2 - yy1)
            iou = inter / (area[i] + area[j] - inter + 1e-10)
            if iou > iou_threshold:
                suppressed[j] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather. Parity: vision/ops.py roi_align."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        # assign each roi to its batch image
        batch_idx = jnp.repeat(jnp.arange(rois_num.shape[0]), rois.shape[0] //
                               max(rois_num.shape[0], 1),
                               total_repeat_length=rois.shape[0]) \
            if rois_num is None else \
            jnp.repeat(jnp.arange(rois_num.shape[0]), rois_num,
                       total_repeat_length=rois.shape[0])
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1e-3)
        roi_h = jnp.maximum(y2 - y1, 1e-3)
        bin_w = roi_w / ow
        bin_h = roi_h / oh
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample points per bin
        ys = y1[:, None, None, None] + bin_h[:, None, None, None] * (
            jnp.arange(oh)[None, :, None, None] +
            (jnp.arange(sr)[None, None, None, :] + 0.5) / sr)
        xs = x1[:, None, None, None] + bin_w[:, None, None, None] * (
            jnp.arange(ow)[None, :, None, None] +
            (jnp.arange(sr)[None, None, None, :] + 0.5) / sr)

        def bilinear(img, yy, xx):
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = yy - y0
            wx = xx - x0
            v = (img[:, y0, x0] * (1 - wy) * (1 - wx) +
                 img[:, y0, x1_] * (1 - wy) * wx +
                 img[:, y1_, x0] * wy * (1 - wx) +
                 img[:, y1_, x1_] * wy * wx)
            return v

        def per_roi(bi, ys_r, xs_r):
            img = feat[bi]  # c,h,w
            # ys_r: (oh, 1, sr) xs_r: (ow, 1, sr) -> grid (oh, ow, sr, sr)
            yy = ys_r[:, None, 0, :, None]  # oh,1,sr,1
            xx = xs_r[None, :, 0, None, :]  # 1,ow,1,sr
            yy = jnp.broadcast_to(yy, (oh, ow, sr, sr))
            xx = jnp.broadcast_to(xx, (oh, ow, sr, sr))
            vals = bilinear(img, yy, xx)  # c,oh,ow,sr,sr
            return jnp.mean(vals, axis=(-1, -2))

        out = jax.vmap(per_roi)(batch_idx, ys, xs)
        return out
    return apply_op("roi_align", _f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=1, aligned=False)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    raise NotImplementedError("psroi_pool planned (position-sensitive variant)")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (mask=None -> v1).

    Parity: `python/paddle/vision/ops.py` deform_conv2d over
    `phi/kernels/deformable_conv_kernel.h`. x (B, Cin, H, W); offset
    (B, 2*dg*kh*kw, Ho, Wo) in (dy, dx) pairs; mask (B, dg*kh*kw, Ho, Wo).

    TPU-native: bilinear sampling as four gathers + weighted sum (vs the
    reference's per-thread CUDA im2col), then one grouped einsum on the
    MXU. Fully differentiable and jit-friendly (static shapes).
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def _f(xa, off, w, *rest):
        rest = list(rest)
        mk = rest.pop(0) if mask is not None else None
        b_ = rest.pop(0) if bias is not None else None
        B, Cin, H, W = xa.shape
        Cout, Cin_g, kh, kw = w.shape
        K = kh * kw
        dg = deformable_groups
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        off = off.reshape(B, dg, K, 2, Ho, Wo)
        # base sampling grid per kernel tap
        ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
        base_y = (s[0] * jnp.arange(Ho)[None, :, None] - p[0]
                  + d[0] * ky.reshape(K, 1, 1))          # (K, Ho, 1)
        base_x = (s[1] * jnp.arange(Wo)[None, None, :] - p[1]
                  + d[1] * kx.reshape(K, 1, 1))          # (K, 1, Wo)
        ys = base_y + off[:, :, :, 0]                    # (B, dg, K, Ho, Wo)
        xs = base_x + off[:, :, :, 1]

        y0 = jnp.floor(ys); x0 = jnp.floor(xs)
        wy = ys - y0; wx = xs - x0

        def gather(yy, xx):
            inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
            yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            # channels split across deformable groups
            xg = xa.reshape(B, dg, Cin // dg, H, W)
            flat = xg.reshape(B, dg, Cin // dg, H * W)
            lin = (yc * W + xc).reshape(B, dg, -1)       # (B, dg, K*Ho*Wo)
            got = jnp.take_along_axis(flat, lin[:, :, None, :], axis=3)
            got = got.reshape(B, dg, Cin // dg, K, Ho, Wo)
            return got * inb[:, :, None].astype(xa.dtype)

        v = (gather(y0, x0) * ((1 - wy) * (1 - wx))[:, :, None]
             + gather(y0, x0 + 1) * ((1 - wy) * wx)[:, :, None]
             + gather(y0 + 1, x0) * (wy * (1 - wx))[:, :, None]
             + gather(y0 + 1, x0 + 1) * (wy * wx)[:, :, None])
        if mk is not None:
            v = v * mk.reshape(B, dg, 1, K, Ho, Wo).astype(xa.dtype)
        v = v.reshape(B, Cin, K, Ho, Wo)
        # grouped contraction: (B, g, Cin/g, K, Ho, Wo) x (g, Cout/g, Cin/g, K)
        vg = v.reshape(B, groups, Cin // groups, K, Ho, Wo)
        wg = w.reshape(groups, Cout // groups, Cin_g, kh * kw)
        out = jnp.einsum("bgckhw,gock->bgohw", vg, wg)
        out = out.reshape(B, Cout, Ho, Wo)
        if b_ is not None:
            out = out + b_.reshape(1, Cout, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op("deform_conv2d", _f, *args)


class DeformConv2D(_Layer):
    """Layer over deform_conv2d (parity: paddle.vision.ops.DeformConv2D) —
    a real nn.Layer so parent models see its parameters."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw), attr=weight_attr)
        self.add_parameter("weight", self.weight)
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), attr=bias_attr,
                                  is_bias=True)
        if self.bias is not None:
            self.add_parameter("bias", self.bias)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


# ------------------------------------------------------------- detection
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (parity: vision/ops.py box_coder
    over phi box_coder kernel). Boxes are (x1, y1, x2, y2)."""
    def _f(pb, tb, *maybe_var):
        var = maybe_var[0] if maybe_var else None
        off = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + off
        ph = pb[:, 3] - pb[:, 1] + off
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + off
            th = tb[:, 3] - tb[:, 1] + off
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx[:, None] - pcx[None, :]) / pw[None, :],
                             (tcy[:, None] - pcy[None, :]) / ph[None, :],
                             jnp.log(tw[:, None] / pw[None, :]),
                             jnp.log(th[:, None] / ph[None, :])], axis=-1)
            if var is not None:
                out = out / var[None, :, :]
            return out
        # decode_center_size: tb (N, M, 4) deltas against the priors
        d = tb
        if var is not None:
            if var.ndim == 2:
                # broadcast along the prior axis (phi box_coder_kernel.cc
                # prior_var_offset switches on axis)
                d = d * (var[None, :, :] if axis == 0 else var[:, None, :])
            else:
                d = d * var
        if axis == 0:
            pcx_, pcy_, pw_, ph_ = (v[None, :] for v in (pcx, pcy, pw, ph))
        else:
            pcx_, pcy_, pw_, ph_ = (v[:, None] for v in (pcx, pcy, pw, ph))
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)

    args = [prior_box, target_box]
    if prior_box_var is not None and not isinstance(prior_box_var,
                                                    (list, tuple)):
        args.append(prior_box_var)
    elif isinstance(prior_box_var, (list, tuple)):
        args.append(Tensor(jnp.broadcast_to(
            jnp.asarray(prior_box_var, jnp.float32),
            (prior_box.shape[0], 4))))
    return apply_op("box_coder", _f, *args)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes per feature-map cell (parity:
    vision/ops.py prior_box)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for ms in min_sizes:
        ratio_boxes = [(ms * np.sqrt(ar), ms / np.sqrt(ar))
                       for ar in ars if abs(ar - 1.0) > 1e-6]
        max_box = None
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            max_box = (np.sqrt(ms * mx), np.sqrt(ms * mx))
        if min_max_aspect_ratios_order:
            # min, max, then ratio boxes (phi prior_box_kernel.cc:107)
            whs.append((ms, ms))
            if max_box:
                whs.append(max_box)
            whs += ratio_boxes
        else:
            # default: min, ratio boxes, max LAST
            whs.append((ms, ms))
            whs += ratio_boxes
            if max_box:
                whs.append(max_box)
    whs = np.asarray(whs, np.float32)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    gx, gy = np.meshgrid(cx, cy)
    centers = np.stack([gx, gy], -1).reshape(fh, fw, 1, 2)
    half = whs[None, None] / 2
    boxes = np.concatenate([
        (centers - half) / np.array([iw, ih], np.float32),
        (centers + half) / np.array([iw, ih], np.float32)], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    boxes = boxes.astype(np.float32)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head predictions into boxes + scores (parity:
    vision/ops.py yolo_box over phi yolo_box kernel)."""
    def _f(a, imgs):
        B, C, H, W = a.shape
        na = len(anchors) // 2
        an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        iou_pred = None
        if iou_aware:
            # layout: na IoU channels first, then the na*(5+cls) head
            iou_pred = jax.nn.sigmoid(a[:, :na].reshape(B, na, H, W))
            a = a[:, na:]
        a = a.reshape(B, na, -1, H, W)
        sxy = float(scale_x_y)
        bias = -0.5 * (sxy - 1.0)
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        cx = (jax.nn.sigmoid(a[:, :, 0]) * sxy + bias
              + gx[None, None, None, :]) / W
        cy = (jax.nn.sigmoid(a[:, :, 1]) * sxy + bias
              + gy[None, None, :, None]) / H
        tw = jnp.exp(jnp.clip(a[:, :, 2], -10, 10)) \
            * an[None, :, 0, None, None]
        th = jnp.exp(jnp.clip(a[:, :, 3], -10, 10)) \
            * an[None, :, 1, None, None]
        w = tw / (W * downsample_ratio)
        h = th / (H * downsample_ratio)
        obj = jax.nn.sigmoid(a[:, :, 4])
        if iou_pred is not None:
            f = float(iou_aware_factor)
            obj = obj ** (1.0 - f) * iou_pred ** f
        cls = jax.nn.sigmoid(a[:, :, 5:5 + class_num])
        imgh = imgs[:, 0].astype(jnp.float32)[:, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None]
        x1 = (cx - w / 2).reshape(B, -1) * imgw
        y1 = (cy - h / 2).reshape(B, -1) * imgh
        x2 = (cx + w / 2).reshape(B, -1) * imgw
        y2 = (cy + h / 2).reshape(B, -1) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1)
        scores = (obj[..., None] * jnp.moveaxis(cls, 2, -1)) \
            .reshape(B, -1, class_num)
        mask = (obj.reshape(B, -1) >= conf_thresh).astype(boxes.dtype)
        return boxes * mask[..., None], scores * mask[..., None]

    return apply_op("yolo_box", _f, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (parity: vision/ops.py yolo_loss): anchor
    assignment by max-IoU at the gt center cell, BCE on xy/obj/cls and
    L1-ish on wh, objectness ignore above IoU threshold."""
    def _f(a, gtb, gtl, *maybe_s):
        B, C, H, W = a.shape
        na = len(anchor_mask)
        an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        an = an_all[jnp.asarray(anchor_mask)]
        a = a.reshape(B, na, 5 + class_num, H, W)
        stride = downsample_ratio
        # gt in [0,1] xywh-center form (paddle convention)
        gx, gy = gtb[..., 0], gtb[..., 1]
        gw, gh = jnp.maximum(gtb[..., 2], 1e-9), jnp.maximum(
            gtb[..., 3], 1e-9)
        valid = (gw > 1e-8)
        ci = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        cj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        # best anchor per gt by wh IoU (anchor units: pixels)
        gwp = gw * W * stride
        ghp = gh * H * stride
        inter = (jnp.minimum(gwp[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(ghp[..., None], an_all[None, None, :, 1]))
        union = (gwp * ghp)[..., None] + an_all[None, None, :, 0] \
            * an_all[None, None, :, 1] - inter
        best = jnp.argmax(inter / union, axis=-1)     # (B, G) global idx
        mask_ids = jnp.asarray(anchor_mask)
        loss = jnp.zeros((B,), jnp.float32)
        eps = 1e-7
        lab_pos = 1.0 - 1.0 / class_num if use_label_smooth else 1.0
        lab_neg = 1.0 / class_num if use_label_smooth else 0.0
        gts = maybe_s[0] if maybe_s else jnp.ones(gtb.shape[:2])
        obj_target = jnp.zeros((B, na, H, W))
        # objectness ignore mask: decoded predictions whose best IoU with
        # any gt exceeds ignore_thresh drop out of the negative loss
        gx_grid = (jax.nn.sigmoid(a[:, :, 0])
                   + jnp.arange(W, dtype=jnp.float32)[None, None, None, :]) / W
        gy_grid = (jax.nn.sigmoid(a[:, :, 1])
                   + jnp.arange(H, dtype=jnp.float32)[None, None, :, None]) / H
        pw_grid = jnp.exp(jnp.clip(a[:, :, 2], -10, 10)) \
            * an[None, :, 0, None, None] / (W * stride)
        ph_grid = jnp.exp(jnp.clip(a[:, :, 3], -10, 10)) \
            * an[None, :, 1, None, None] / (H * stride)
        px1 = gx_grid - pw_grid / 2
        py1 = gy_grid - ph_grid / 2
        px2 = gx_grid + pw_grid / 2
        py2 = gy_grid + ph_grid / 2
        best_iou = jnp.zeros((B, na, H, W))
        for g in range(gtb.shape[1]):
            bx1 = (gx[:, g] - gw[:, g] / 2)[:, None, None, None]
            by1 = (gy[:, g] - gh[:, g] / 2)[:, None, None, None]
            bx2 = (gx[:, g] + gw[:, g] / 2)[:, None, None, None]
            by2 = (gy[:, g] + gh[:, g] / 2)[:, None, None, None]
            iw_ = jnp.maximum(jnp.minimum(px2, bx2)
                              - jnp.maximum(px1, bx1), 0)
            ih_ = jnp.maximum(jnp.minimum(py2, by2)
                              - jnp.maximum(py1, by1), 0)
            inter_ = iw_ * ih_
            uni = (pw_grid * ph_grid
                   + (gw[:, g] * gh[:, g])[:, None, None, None] - inter_)
            iou_g = jnp.where(valid[:, g][:, None, None, None],
                              inter_ / jnp.maximum(uni, 1e-9), 0.0)
            best_iou = jnp.maximum(best_iou, iou_g)
        ignore = best_iou > ignore_thresh
        for g in range(gtb.shape[1]):
            for local_a in range(na):
                sel = valid[:, g] & (best[:, g] == mask_ids[local_a])
                px = jax.nn.sigmoid(
                    a[jnp.arange(B), local_a, 0, cj[:, g], ci[:, g]])
                py = jax.nn.sigmoid(
                    a[jnp.arange(B), local_a, 1, cj[:, g], ci[:, g]])
                pw = a[jnp.arange(B), local_a, 2, cj[:, g], ci[:, g]]
                ph = a[jnp.arange(B), local_a, 3, cj[:, g], ci[:, g]]
                tx = gx[:, g] * W - ci[:, g]
                ty = gy[:, g] * H - cj[:, g]
                tw = jnp.log(gwp[:, g] / an[local_a, 0])
                th = jnp.log(ghp[:, g] / an[local_a, 1])
                scale = 2.0 - gw[:, g] * gh[:, g]
                l_xy = (-(tx * jnp.log(px + eps)
                          + (1 - tx) * jnp.log(1 - px + eps))
                        - (ty * jnp.log(py + eps)
                           + (1 - ty) * jnp.log(1 - py + eps))) * scale
                l_wh = (jnp.abs(pw - tw) + jnp.abs(ph - th)) * scale
                pc = jax.nn.sigmoid(
                    a[jnp.arange(B), local_a, 5:, cj[:, g], ci[:, g]])
                onehot = jax.nn.one_hot(gtl[:, g], class_num)
                tcls = onehot * lab_pos + (1 - onehot) * lab_neg
                l_cls = -(tcls * jnp.log(pc + eps)
                          + (1 - tcls) * jnp.log(1 - pc + eps)).sum(-1)
                loss = loss + jnp.where(sel, (l_xy + l_wh + l_cls)
                                        * gts[:, g], 0.0)
                obj_target = obj_target.at[
                    jnp.arange(B), local_a, cj[:, g], ci[:, g]].max(
                    jnp.where(sel, 1.0, 0.0))
        pobj = jax.nn.sigmoid(a[:, :, 4])
        neg_w = jnp.where(ignore & (obj_target == 0), 0.0, 1.0)
        l_obj = -(obj_target * jnp.log(pobj + eps)
                  + (1 - obj_target) * neg_w * jnp.log(1 - pobj + eps))
        loss = loss + l_obj.sum((1, 2, 3))
        return loss

    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(gt_score)
    return apply_op("yolo_loss", _f, *args)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; parity: vision/ops.py matrix_nms): decay each
    box's score by its max-IoU overlap with higher-scored boxes of the
    same class — no hard suppression loop. Eager-only (data-dependent
    output count)."""
    bb = np.asarray(bboxes._data if isinstance(bboxes, Tensor) else bboxes)
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    B, C, M = sc.shape
    off = 0.0 if normalized else 1.0
    outs, idxs, nums = [], [], []
    for b in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            keep = np.where(sc[b, c] > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[b, c, keep])][:nms_top_k]
            boxes = bb[b, order]
            s = sc[b, c, order]
            x1, y1, x2, y2 = boxes.T
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            iw = np.maximum(ix2 - ix1 + off, 0)
            ih = np.maximum(iy2 - iy1 + off, 0)
            iou = iw * ih / (area[:, None] + area[None, :] - iw * ih)
            iou = np.triu(iou, 1)                     # j > i: i higher
            # comp_i = max IoU of box i with boxes scored higher than i
            comp = iou.max(0)
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma).min(0)
            else:
                decay = ((1 - iou) / np.maximum(1 - comp[:, None],
                                                1e-9)).min(0)
            new_s = s * decay
            for i, o in enumerate(order):
                if new_s[i] > post_threshold:
                    dets.append((float(new_s[i]), c, b, o))
        dets.sort(key=lambda d: -d[0])
        dets = dets[:keep_top_k]
        out = np.array([[c, s2, *bb[b, o]] for s2, c, _, o in dets],
                       np.float32).reshape(-1, 6)
        outs.append(out)
        idxs.extend(b * M + o for _, _, _, o in dets)  # flattened index
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs, 0)
                             if outs else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(idxs, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(res) if len(res) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (parity: vision/ops.py
    distribute_fpn_proposals). Eager-only."""
    rois = np.asarray(fpn_rois._data if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        rn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                        else rois_num).astype(np.int64)
        img_of = np.repeat(np.arange(rn.shape[0]), rn)
    else:
        rn = np.asarray([rois.shape[0]], np.int64)
        img_of = np.zeros(rois.shape[0], np.int64)
    outs, index, nums = [], [], []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        index.extend(sel.tolist())
        # per-image roi count at this level (reference rois_num_per_level)
        per_img = np.asarray([(img_of[sel] == b).sum()
                              for b in range(rn.shape[0])], np.int32)
        nums.append(Tensor(jnp.asarray(per_img)))
    restore = np.empty(len(index), np.int32)
    restore[np.asarray(index, np.int64)] = np.arange(len(index))
    return outs, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation: decode deltas vs anchors, top-k, clip,
    filter small, NMS (parity: vision/ops.py generate_proposals).
    Eager-only."""
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    bd = np.asarray(bbox_deltas._data if isinstance(bbox_deltas, Tensor)
                    else bbox_deltas)
    an = np.asarray(anchors._data if isinstance(anchors, Tensor)
                    else anchors).reshape(-1, 4)
    var = np.asarray(variances._data if isinstance(variances, Tensor)
                     else variances).reshape(-1, 4)
    ims = np.asarray(img_size._data if isinstance(img_size, Tensor)
                     else img_size)
    B = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, nums = [], [], []
    for b in range(B):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = bd[b].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a_, v_ = s[order], d[order], an[order % an.shape[0]] \
            if an.shape[0] != s.shape[0] else an[order], var[order % var.shape[0]] \
            if var.shape[0] != s.shape[0] else var[order]
        aw = a_[:, 2] - a_[:, 0] + off
        ah = a_[:, 3] - a_[:, 1] + off
        acx = a_[:, 0] + aw / 2
        acy = a_[:, 1] + ah / 2
        cx = v_[:, 0] * d[:, 0] * aw + acx
        cy = v_[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.clip(v_[:, 2] * d[:, 2], -10, 10)) * aw
        h = np.exp(np.clip(v_[:, 3] * d[:, 3], -10, 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], -1)
        ih, iw = ims[b, 0], ims[b, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keep = np.where((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                        & (boxes[:, 3] - boxes[:, 1] + off >= min_size))[0]
        boxes, s = boxes[keep], s[keep]
        # plain NMS
        sel = []
        idx = np.argsort(-s)
        while idx.size and len(sel) < post_nms_top_n:
            i = idx[0]
            sel.append(i)
            if idx.size == 1:
                break
            xx1 = np.maximum(boxes[i, 0], boxes[idx[1:], 0])
            yy1 = np.maximum(boxes[i, 1], boxes[idx[1:], 1])
            xx2 = np.minimum(boxes[i, 2], boxes[idx[1:], 2])
            yy2 = np.minimum(boxes[i, 3], boxes[idx[1:], 3])
            iw_ = np.maximum(xx2 - xx1 + off, 0)
            ih_ = np.maximum(yy2 - yy1 + off, 0)
            ai = (boxes[i, 2] - boxes[i, 0] + off) \
                * (boxes[i, 3] - boxes[i, 1] + off)
            aj = (boxes[idx[1:], 2] - boxes[idx[1:], 0] + off) \
                * (boxes[idx[1:], 3] - boxes[idx[1:], 1] + off)
            iou = iw_ * ih_ / (ai + aj - iw_ * ih_)
            idx = idx[1:][iou <= nms_thresh]
        all_rois.append(boxes[sel])
        all_probs.append(s[sel])
        nums.append(len(sel))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0).astype(
        np.float32)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0).astype(
        np.float32)[:, None]))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


yolov3_loss = yolo_loss


class RoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._cfg = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._cfg[0], self._cfg[1])


class RoIAlign(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._cfg = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._cfg[0], self._cfg[1],
                         aligned=aligned)


class PSRoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._cfg = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._cfg[0], self._cfg[1])


def read_file(path, name=None):
    """Raw file bytes as a uint8 tensor (parity: vision/ops.py
    read_file)."""
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to (C, H, W) uint8 (parity:
    vision/ops.py decode_jpeg; host-side via PIL)."""
    import io
    from PIL import Image
    data = np.asarray(x._data if isinstance(x, Tensor) else x,
                      np.uint8).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
