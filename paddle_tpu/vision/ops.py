"""Vision ops: nms, roi_align, box utils.

Parity: reference `python/paddle/vision/ops.py` (subset: nms, roi_align,
box_coder-adjacent utilities, deform_conv2d is a planned kernel).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.dispatch import apply_op
from ..nn.layer.layers import Layer as _Layer

__all__ = ["nms", "roi_align", "box_area", "box_iou", "psroi_pool", "roi_pool", "deform_conv2d", "DeformConv2D"]


def box_area(boxes):
    return apply_op("box_area",
                    lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes)


def box_iou(boxes1, boxes2):
    def _f(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply_op("box_iou", _f, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host-side; dynamic output shape). Parity: vision/ops.py nms."""
    b = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._data) if scores is not None else None
    order = np.argsort(-s) if s is not None else np.arange(len(b))
    if category_idxs is not None:
        cats = np.asarray(category_idxs._data if isinstance(category_idxs, Tensor)
                          else category_idxs)
    else:
        cats = np.zeros(len(b), np.int64)
    keep = []
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if j == i or suppressed[j] or cats[j] != cats[i]:
                continue
            xx1 = max(b[i, 0], b[j, 0])
            yy1 = max(b[i, 1], b[j, 1])
            xx2 = min(b[i, 2], b[j, 2])
            yy2 = min(b[i, 3], b[j, 3])
            inter = max(0.0, xx2 - xx1) * max(0.0, yy2 - yy1)
            iou = inter / (area[i] + area[j] - inter + 1e-10)
            if iou > iou_threshold:
                suppressed[j] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather. Parity: vision/ops.py roi_align."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        # assign each roi to its batch image
        batch_idx = jnp.repeat(jnp.arange(rois_num.shape[0]), rois.shape[0] //
                               max(rois_num.shape[0], 1),
                               total_repeat_length=rois.shape[0]) \
            if rois_num is None else \
            jnp.repeat(jnp.arange(rois_num.shape[0]), rois_num,
                       total_repeat_length=rois.shape[0])
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1e-3)
        roi_h = jnp.maximum(y2 - y1, 1e-3)
        bin_w = roi_w / ow
        bin_h = roi_h / oh
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample points per bin
        ys = y1[:, None, None, None] + bin_h[:, None, None, None] * (
            jnp.arange(oh)[None, :, None, None] +
            (jnp.arange(sr)[None, None, None, :] + 0.5) / sr)
        xs = x1[:, None, None, None] + bin_w[:, None, None, None] * (
            jnp.arange(ow)[None, :, None, None] +
            (jnp.arange(sr)[None, None, None, :] + 0.5) / sr)

        def bilinear(img, yy, xx):
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = yy - y0
            wx = xx - x0
            v = (img[:, y0, x0] * (1 - wy) * (1 - wx) +
                 img[:, y0, x1_] * (1 - wy) * wx +
                 img[:, y1_, x0] * wy * (1 - wx) +
                 img[:, y1_, x1_] * wy * wx)
            return v

        def per_roi(bi, ys_r, xs_r):
            img = feat[bi]  # c,h,w
            # ys_r: (oh, 1, sr) xs_r: (ow, 1, sr) -> grid (oh, ow, sr, sr)
            yy = ys_r[:, None, 0, :, None]  # oh,1,sr,1
            xx = xs_r[None, :, 0, None, :]  # 1,ow,1,sr
            yy = jnp.broadcast_to(yy, (oh, ow, sr, sr))
            xx = jnp.broadcast_to(xx, (oh, ow, sr, sr))
            vals = bilinear(img, yy, xx)  # c,oh,ow,sr,sr
            return jnp.mean(vals, axis=(-1, -2))

        out = jax.vmap(per_roi)(batch_idx, ys, xs)
        return out
    return apply_op("roi_align", _f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=1, aligned=False)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    raise NotImplementedError("psroi_pool planned (position-sensitive variant)")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (mask=None -> v1).

    Parity: `python/paddle/vision/ops.py` deform_conv2d over
    `phi/kernels/deformable_conv_kernel.h`. x (B, Cin, H, W); offset
    (B, 2*dg*kh*kw, Ho, Wo) in (dy, dx) pairs; mask (B, dg*kh*kw, Ho, Wo).

    TPU-native: bilinear sampling as four gathers + weighted sum (vs the
    reference's per-thread CUDA im2col), then one grouped einsum on the
    MXU. Fully differentiable and jit-friendly (static shapes).
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def _f(xa, off, w, *rest):
        rest = list(rest)
        mk = rest.pop(0) if mask is not None else None
        b_ = rest.pop(0) if bias is not None else None
        B, Cin, H, W = xa.shape
        Cout, Cin_g, kh, kw = w.shape
        K = kh * kw
        dg = deformable_groups
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        off = off.reshape(B, dg, K, 2, Ho, Wo)
        # base sampling grid per kernel tap
        ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
        base_y = (s[0] * jnp.arange(Ho)[None, :, None] - p[0]
                  + d[0] * ky.reshape(K, 1, 1))          # (K, Ho, 1)
        base_x = (s[1] * jnp.arange(Wo)[None, None, :] - p[1]
                  + d[1] * kx.reshape(K, 1, 1))          # (K, 1, Wo)
        ys = base_y + off[:, :, :, 0]                    # (B, dg, K, Ho, Wo)
        xs = base_x + off[:, :, :, 1]

        y0 = jnp.floor(ys); x0 = jnp.floor(xs)
        wy = ys - y0; wx = xs - x0

        def gather(yy, xx):
            inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1))
            yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            # channels split across deformable groups
            xg = xa.reshape(B, dg, Cin // dg, H, W)
            flat = xg.reshape(B, dg, Cin // dg, H * W)
            lin = (yc * W + xc).reshape(B, dg, -1)       # (B, dg, K*Ho*Wo)
            got = jnp.take_along_axis(flat, lin[:, :, None, :], axis=3)
            got = got.reshape(B, dg, Cin // dg, K, Ho, Wo)
            return got * inb[:, :, None].astype(xa.dtype)

        v = (gather(y0, x0) * ((1 - wy) * (1 - wx))[:, :, None]
             + gather(y0, x0 + 1) * ((1 - wy) * wx)[:, :, None]
             + gather(y0 + 1, x0) * (wy * (1 - wx))[:, :, None]
             + gather(y0 + 1, x0 + 1) * (wy * wx)[:, :, None])
        if mk is not None:
            v = v * mk.reshape(B, dg, 1, K, Ho, Wo).astype(xa.dtype)
        v = v.reshape(B, Cin, K, Ho, Wo)
        # grouped contraction: (B, g, Cin/g, K, Ho, Wo) x (g, Cout/g, Cin/g, K)
        vg = v.reshape(B, groups, Cin // groups, K, Ho, Wo)
        wg = w.reshape(groups, Cout // groups, Cin_g, kh * kw)
        out = jnp.einsum("bgckhw,gock->bgohw", vg, wg)
        out = out.reshape(B, Cout, Ho, Wo)
        if b_ is not None:
            out = out + b_.reshape(1, Cout, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op("deform_conv2d", _f, *args)


class DeformConv2D(_Layer):
    """Layer over deform_conv2d (parity: paddle.vision.ops.DeformConv2D) —
    a real nn.Layer so parent models see its parameters."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw), attr=weight_attr)
        self.add_parameter("weight", self.weight)
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), attr=bias_attr,
                                  is_bias=True)
        if self.bias is not None:
            self.add_parameter("bias", self.bias)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)
