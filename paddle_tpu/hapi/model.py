"""paddle.Model — Keras-like high-level train/eval/predict loop.

Parity: reference `python/paddle/hapi/model.py` (Model.prepare/fit/evaluate/
predict/save/load). The train step runs through the same eager tape; pass
`jit=True` to prepare() to compile the whole step with to_static.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._jit_step = None

    def prepare(self, optimizer=None, loss=None, metrics=None, jit=False,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics is not None else [])
        if jit:
            from ..jit import to_static
            self._jit_step = to_static(
                self._train_step_fn,
                state_objects=[self.network, self._optimizer])
        return self

    # ------------------------------------------------------------ core steps
    def _train_step_fn(self, *data):
        inputs, labels = self._split(data)
        self.network.train()
        outputs = self.network(*inputs)
        losses = self._loss(outputs, *labels) if self._loss else outputs
        loss = losses if isinstance(losses, Tensor) else losses[0]
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        data = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        if labels is not None:
            data += list(labels if isinstance(labels, (list, tuple)) else [labels])
        if self._jit_step is not None:
            loss = self._jit_step(*data)
        else:
            loss = self._train_step_fn(*data)
        return [float(np.asarray(loss._data))]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        ins = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        labs = list(labels if isinstance(labels, (list, tuple)) else [labels]) \
            if labels is not None else []
        self.network.eval()
        with no_grad():
            outputs = self.network(*ins)
            loss = self._loss(outputs, *labs) if self._loss else None
            metrics = []
            for m in self._metrics:
                m.update(m.compute(outputs, *labs))
                metrics.append(m.accumulate())
        return ([float(np.asarray(loss._data))] if loss is not None else []), metrics

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        ins = list(inputs if isinstance(inputs, (list, tuple)) else [inputs])
        self.network.eval()
        with no_grad():
            out = self.network(*ins)
        return [np.asarray(o._data) for o in
                (out if isinstance(out, (list, tuple)) else [out])]

    def _split(self, data):
        """Split a flat data tuple into (inputs, labels): convention is the
        last element is the label (hapi default when no input spec given)."""
        if len(data) == 1:
            return list(data), []
        return list(data[:-1]), [data[-1]]

    # ----------------------------------------------------------------- loops
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        cbks = CallbackList((callbacks or []) + [ProgBarLogger(log_freq, verbose)])
        cbks.set_model(self)
        cbks.set_params({"epochs": epochs, "verbose": verbose})
        cbks.on_train_begin()
        step_total = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = self.train_batch(batch[:-1] if len(batch) > 1 else batch,
                                        batch[-1:] if len(batch) > 1 else None)
                cbks.on_train_batch_end(step, {"loss": loss})
                step_total += 1
                if num_iters is not None and step_total >= num_iters:
                    break
            cbks.on_epoch_end(epoch)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=verbose,
                              num_workers=num_workers)
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            loss, _ = self.eval_batch(batch[:-1] if len(batch) > 1 else batch,
                                      batch[-1:] if len(batch) > 1 else None)
            losses.extend(loss)
            if num_iters is not None and step + 1 >= num_iters:
                break
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            result[m.name() if isinstance(m.name(), str) else m.name()[0]] = \
                m.accumulate()
        if verbose:
            print("Eval:", result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            outputs.append(self.predict_batch(batch))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # --------------------------------------------------------------- save/load
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        lines = [f"{type(self.network).__name__}: {n_params:,} parameters"]
        print("\n".join(lines))
        return {"total_params": n_params}
